"""Unified geometric warper + synthetic training-pair generators.

TPU-native re-design of the reference's transformation stack
(geotnf/transformation.py:14-368):

* `GeometricTnf` (geotnf/transformation.py:74-140) becomes the pure function
  `geometric_transform` plus the grid factory `make_sampling_grid` — no
  mutable Module state, no `use_cuda` flags; everything jits and shards.
* `ComposedGeometricTnf` (geotnf/transformation.py:14-72) becomes
  `compose_aff_tps_grid` / `composed_transform`: the affine and TPS grids are
  composed by bilinearly sampling the affine grid (as a 2-channel image) at
  the TPS grid positions, with 1e10 out-of-bounds sentinels exactly like the
  reference so downstream `grid_sample` zero-pads composed OOB regions.
* The `SynthPairTnf` family (geotnf/transformation.py:144-368) becomes the
  functional generators `synth_pair` / `synth_two_pair` / `synth_two_stage` /
  `synth_two_stage_two_pair`: image batch + theta batch in, training-pair
  dict out. Randomness lives with the caller (jax.random / dataset RNG), not
  hidden module state.

Semantics parity notes (pinned by tests/test_transform.py):
* `offset_factor` divides the base grid before the transform and multiplies
  the resulting grid after it (geotnf/transformation.py:95-97,128-129) — for
  an affine map this scales only the translation column.
* `padding_factor`/`crop_factor` scale the final sampling grid
  (geotnf/transformation.py:124-126), matching the symmetric-padding +
  center-crop training recipe.
* `symmetric_image_pad` reflect-pads by `int(dim * padding_factor)` on each
  side, mirroring edge-inclusive ("symmetric" mode) like the index-select
  construction at geotnf/transformation.py:207-223.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .grid import affine_grid, grid_sample, identity_grid
from .tps import TpsGrid

OOB_SENTINEL = 1e10


def make_sampling_grid(
    theta,
    out_h: int,
    out_w: int,
    geometric_model: str = "affine",
    tps_grid_size: int = 3,
    tps_reg_factor: float = 0.0,
    offset_factor: Optional[float] = None,
):
    """Sampling grid [b, out_h, out_w, 2] for affine or TPS parameters.

    theta: [b, 2, 3] / [b, 6] for affine; [b, 2*grid_size^2] for TPS.
    """
    if geometric_model == "affine":
        theta = jnp.reshape(theta, (-1, 2, 3))
        if offset_factor is None:
            return affine_grid(theta, out_h, out_w)
        # Base grid divided by offset_factor, result multiplied back
        # (geotnf/transformation.py:95-97,128-129): net effect is the
        # translation column scaled by offset_factor.
        scaled = theta.at[:, :, 2].multiply(offset_factor)
        return affine_grid(scaled, out_h, out_w)
    if geometric_model == "tps":
        tps = TpsGrid(grid_size=tps_grid_size, reg_factor=tps_reg_factor)
        grid = tps.grid(theta, out_h, out_w)
        if offset_factor is not None:
            # grid points pre-divided then output post-multiplied: for the
            # (nonlinear) TPS map these do not cancel, so apply literally.
            xs = jnp.linspace(-1.0, 1.0, out_w) / offset_factor
            ys = jnp.linspace(-1.0, 1.0, out_h) / offset_factor
            gx, gy = jnp.meshgrid(xs, ys)
            pts = jnp.stack([gx, gy], axis=-1)
            grid = tps.apply(theta, pts, batched=False) * offset_factor
        return grid
    raise ValueError(f"unknown geometric_model {geometric_model!r}")


def geometric_transform(
    image,
    theta=None,
    geometric_model: str = "affine",
    out_h: int = 240,
    out_w: int = 240,
    padding_factor: float = 1.0,
    crop_factor: float = 1.0,
    tps_grid_size: int = 3,
    tps_reg_factor: float = 0.0,
    offset_factor: Optional[float] = None,
    return_sampling_grid: bool = False,
):
    """Warp an NCHW batch by affine/TPS params (ref GeometricTnf.__call__).

    With `theta=None` this is a corner-aligned bilinear resize scaled by
    `padding_factor * crop_factor` — the identity path the reference uses
    both for dataset resizing and for the synth-pair center crop.
    """
    b = 1 if image is None else image.shape[0]
    if theta is None:
        grid = identity_grid(b, out_h, out_w)
    else:
        grid = make_sampling_grid(
            theta,
            out_h,
            out_w,
            geometric_model=geometric_model,
            tps_grid_size=tps_grid_size,
            tps_reg_factor=tps_reg_factor,
            offset_factor=offset_factor,
        )
    if padding_factor != 1.0 or crop_factor != 1.0:
        grid = grid * (padding_factor * crop_factor)
    if image is None:
        return grid
    warped = grid_sample(image, grid.astype(image.dtype))
    if return_sampling_grid:
        return warped, grid
    return warped


def _mask_oob(grid):
    """Replace grid rows whose (x, y) fall outside (-1, 1) with -1e10.

    Matches the sentinel construction at geotnf/transformation.py:54-58: the
    composed grid then samples far outside the image and zero-pads.
    """
    return _mask_oob_like(grid, grid)


def compose_aff_tps_grid(
    theta_aff,
    theta_tps,
    out_h: int = 240,
    out_w: int = 240,
    tps_grid_size: int = 3,
    tps_reg_factor: float = 0.0,
    padding_crop_factor: Optional[float] = None,
):
    """Composed affine∘TPS sampling grid (ref ComposedGeometricTnf).

    The affine grid (as a 2-channel image) is bilinearly sampled at the TPS
    grid positions; out-of-bounds regions of either stage are pushed to the
    1e10 sentinel so the final image sample zero-pads them.
    """
    aff_offset = padding_crop_factor if padding_crop_factor is not None else 1.0
    grid_aff = make_sampling_grid(
        theta_aff, out_h, out_w, "affine", offset_factor=aff_offset
    )
    grid_tps = make_sampling_grid(
        theta_tps,
        out_h,
        out_w,
        "tps",
        tps_grid_size=tps_grid_size,
        tps_reg_factor=tps_reg_factor,
    )
    if padding_crop_factor is not None:
        grid_tps = grid_tps * padding_crop_factor

    grid_aff_m = _mask_oob(grid_aff)
    # Sample the affine grid (channels-first [b, 2, H, W]) at TPS positions.
    as_image = jnp.moveaxis(grid_aff_m, -1, 1)
    composed = jnp.moveaxis(grid_sample(as_image, grid_tps), 1, -1)
    return _mask_oob_like(grid_tps, composed)


def _mask_oob_like(reference_grid, grid):
    """Sentinel-mask `grid` where `reference_grid` is out of bounds."""
    inb = (
        (reference_grid[..., 0] > -1.0)
        & (reference_grid[..., 0] < 1.0)
        & (reference_grid[..., 1] > -1.0)
        & (reference_grid[..., 1] < 1.0)
    )[..., None]
    return jnp.where(inb, grid, -OOB_SENTINEL)


def composed_transform(
    image,
    theta_aff,
    theta_tps,
    out_h: int = 240,
    out_w: int = 240,
    tps_grid_size: int = 3,
    tps_reg_factor: float = 0.0,
    padding_crop_factor: Optional[float] = None,
):
    """Warp an NCHW batch by the composed affine+TPS transform."""
    grid = compose_aff_tps_grid(
        theta_aff,
        theta_tps,
        out_h,
        out_w,
        tps_grid_size=tps_grid_size,
        tps_reg_factor=tps_reg_factor,
        padding_crop_factor=padding_crop_factor,
    )
    return grid_sample(image, grid.astype(image.dtype))


def symmetric_image_pad(image, padding_factor: float):
    """Mirror-pad an NCHW batch by int(dim*padding_factor) per side."""
    h, w = image.shape[2], image.shape[3]
    pad_h, pad_w = int(h * padding_factor), int(w * padding_factor)
    left = image[:, :, :, :pad_w][:, :, :, ::-1]
    right = image[:, :, :, w - pad_w :][:, :, :, ::-1]
    image = jnp.concatenate([left, image, right], axis=3)
    top = image[:, :, :pad_h, :][:, :, ::-1, :]
    bottom = image[:, :, h - pad_h :, :][:, :, ::-1, :]
    return jnp.concatenate([top, image, bottom], axis=2)


def _crop_and_warp(image, padding_factor, crop_factor, out_h, out_w):
    """Shared preamble of every synth generator: pad + identity center crop."""
    padded = symmetric_image_pad(image, padding_factor)
    cropped = geometric_transform(
        padded,
        None,
        out_h=out_h,
        out_w=out_w,
        padding_factor=padding_factor,
        crop_factor=crop_factor,
    )
    return padded, cropped


def synth_pair(
    image,
    theta,
    geometric_model: str = "affine",
    supervision: str = "strong",
    crop_factor: float = 9 / 16,
    output_size=(240, 240),
    padding_factor: float = 0.5,
    tps_grid_size: int = 3,
):
    """Synthetic training pair from one image batch (ref SynthPairTnf).

    strong: {source, target=warp(source-region), theta_GT}.
    weak: first half of the batch are positive pairs (source, warped source),
    second half negatives (source_i, crop_j from the other half) — the
    index-shuffle construction of geotnf/transformation.py:195-205.
    """
    out_h, out_w = output_size
    padded, cropped = _crop_and_warp(
        image, padding_factor, crop_factor, out_h, out_w
    )
    warped = geometric_transform(
        padded,
        theta,
        geometric_model=geometric_model,
        out_h=out_h,
        out_w=out_w,
        padding_factor=padding_factor,
        crop_factor=crop_factor,
        tps_grid_size=tps_grid_size,
    )
    if supervision == "strong":
        return {"source_image": cropped, "target_image": warped, "theta_GT": theta}
    if supervision == "weak":
        b = image.shape[0]
        if b % 2:
            raise ValueError(
                "weak supervision pairs the batch halves; batch size must "
                f"be even, got {b}"
            )
        half = b // 2
        source = jnp.concatenate([cropped[:half], cropped[:half]], axis=0)
        target = jnp.concatenate([warped[:half], cropped[half:]], axis=0)
        return {"source_image": source, "target_image": target, "theta_GT": theta}
    raise ValueError(f"unknown supervision {supervision!r}")


def synth_two_pair(
    image,
    theta,
    crop_factor: float = 9 / 16,
    output_size=(240, 240),
    padding_factor: float = 0.5,
    tps_grid_size: int = 3,
):
    """One source, two targets (affine and TPS) — ref SynthTwoPairTnf.

    theta: [b, 6 + 2*grid_size^2], affine params first.
    """
    out_h, out_w = output_size
    theta_aff, theta_tps = theta[:, :6], theta[:, 6:]
    padded, cropped = _crop_and_warp(
        image, padding_factor, crop_factor, out_h, out_w
    )
    kwargs = dict(
        out_h=out_h,
        out_w=out_w,
        padding_factor=padding_factor,
        crop_factor=crop_factor,
    )
    warped_aff = geometric_transform(padded, theta_aff, "affine", **kwargs)
    warped_tps = geometric_transform(
        padded, theta_tps, "tps", tps_grid_size=tps_grid_size, **kwargs
    )
    return {
        "source_image": cropped,
        "target_image_aff": warped_aff,
        "target_image_tps": warped_tps,
        "theta_GT_aff": theta_aff,
        "theta_GT_tps": theta_tps,
    }


def synth_two_stage(
    image,
    theta,
    crop_factor: float = 9 / 16,
    output_size=(240, 240),
    padding_factor: float = 0.5,
    tps_grid_size: int = 3,
):
    """Source + composed affine∘TPS target — ref SynthTwoStageTnf."""
    out_h, out_w = output_size
    theta_aff, theta_tps = theta[:, :6], theta[:, 6:]
    padded, cropped = _crop_and_warp(
        image, padding_factor, crop_factor, out_h, out_w
    )
    warped = composed_transform(
        padded,
        theta_aff,
        theta_tps,
        out_h=out_h,
        out_w=out_w,
        tps_grid_size=tps_grid_size,
        padding_crop_factor=padding_factor * crop_factor,
    )
    return {
        "source_image": cropped,
        "target_image": warped,
        "theta_GT_aff": theta_aff,
        "theta_GT_tps": theta_tps,
    }


def synth_two_stage_two_pair(
    image,
    theta,
    crop_factor: float = 9 / 16,
    output_size=(240, 240),
    padding_factor: float = 0.5,
    tps_grid_size: int = 3,
):
    """Affine pair + TPS pair sharing one composed target — ref
    SynthTwoStageTwoPairTnf (geotnf/transformation.py:264-320)."""
    out_h, out_w = output_size
    theta_aff, theta_tps = theta[:, :6], theta[:, 6:]
    padded, cropped = _crop_and_warp(
        image, padding_factor, crop_factor, out_h, out_w
    )
    kwargs = dict(out_h=out_h, out_w=out_w)
    target_tps = composed_transform(
        padded,
        theta_aff,
        theta_tps,
        tps_grid_size=tps_grid_size,
        padding_crop_factor=padding_factor * crop_factor,
        **kwargs,
    )
    target_aff = geometric_transform(
        padded,
        theta_aff,
        "affine",
        padding_factor=padding_factor,
        crop_factor=crop_factor,
        **kwargs,
    )
    source_tps = geometric_transform(cropped, theta_aff, "affine", **kwargs)
    return {
        "source_image_aff": cropped,
        "target_image_aff": target_aff,
        "source_image_tps": source_tps,
        "target_image_tps": target_tps,
        "theta_GT_aff": theta_aff,
        "theta_GT_tps": theta_tps,
    }
