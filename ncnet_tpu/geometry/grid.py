"""Sampling-grid generation and bilinear grid sampling.

Reproduces the sampling semantics the reference model was trained with
(PyTorch 0.3 `F.affine_grid` / `F.grid_sample`, reached through
geotnf/transformation.py:371-423 and :122-135 of the reference tree):

* corner alignment ("align_corners=True"): normalized coord -1 maps to the
  center of the first pixel and +1 to the center of the last pixel;
* zero padding outside the image: out-of-range bilinear taps contribute 0.

Getting this wrong silently shifts every downstream PCK number (SURVEY.md §7
"hard parts" item 2), so the unit tests pin these functions against
`torch.nn.functional.grid_sample(..., align_corners=True)` on CPU.

Layout convention throughout the framework is NCHW for images (matching the
correlation-tensor layout [b, 1, iA, jA, iB, jB]) and [b, H, W, 2] for grids
with (x, y) channel order.
"""

from __future__ import annotations

import jax.numpy as jnp


def affine_grid(theta, out_h, out_w):
    """Generate a sampling grid from batched 2x3 affine matrices.

    Args:
      theta: [b, 2, 3] affine parameters (row 0 produces x', row 1 y').
      out_h, out_w: static output grid size.

    Returns:
      [b, out_h, out_w, 2] grid of (x, y) normalized sampling locations.
    """
    theta = jnp.reshape(theta, (-1, 2, 3))
    xs = jnp.linspace(-1.0, 1.0, out_w)
    ys = jnp.linspace(-1.0, 1.0, out_h)
    gx, gy = jnp.meshgrid(xs, ys)  # each [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [H, W, 3]
    # [b, H, W, 2] = base [H, W, 3] . theta^T [b, 3, 2]
    grid = jnp.einsum("hwk,bjk->bhwj", base, theta)
    return grid


def identity_grid(batch, out_h, out_w):
    """Identity sampling grid (pure bilinear resize when sampled)."""
    theta = jnp.broadcast_to(
        jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]], dtype=jnp.float32),
        (batch, 2, 3),
    )
    return affine_grid(theta, out_h, out_w)


def grid_sample(image, grid):
    """Bilinear sampling with corner-aligned coords and zero padding.

    Args:
      image: [b, c, h, w].
      grid: [b, H, W, 2] normalized (x, y) sampling locations.

    Returns:
      [b, c, H, W] sampled output.
    """
    b, c, h, w = image.shape
    x = grid[..., 0]
    y = grid[..., 1]
    # Corner-aligned unnormalization to 0-indexed continuous pixel coords.
    ix = (x + 1.0) * (w - 1) / 2.0
    iy = (y + 1.0) * (h - 1) / 2.0

    ix0 = jnp.floor(ix)
    iy0 = jnp.floor(iy)
    ix1 = ix0 + 1
    iy1 = iy0 + 1

    wx1 = ix - ix0
    wy1 = iy - iy0
    wx0 = 1.0 - wx1
    wy0 = 1.0 - wy1

    def gather(iy_t, ix_t):
        """Gather image values at integer (iy_t, ix_t), zero outside."""
        valid = (iy_t >= 0) & (iy_t <= h - 1) & (ix_t >= 0) & (ix_t <= w - 1)
        iy_c = jnp.clip(iy_t, 0, h - 1).astype(jnp.int32)
        ix_c = jnp.clip(ix_t, 0, w - 1).astype(jnp.int32)
        flat = image.reshape(b, c, h * w)
        idx = (iy_c * w + ix_c).reshape(b, -1)  # [b, H*W]
        vals = jnp.take_along_axis(flat, idx[:, None, :], axis=2)
        vals = vals.reshape(b, c, *iy_t.shape[1:])
        return vals * valid[:, None].astype(image.dtype)

    out = (
        gather(iy0, ix0) * (wy0 * wx0)[:, None]
        + gather(iy0, ix1) * (wy0 * wx1)[:, None]
        + gather(iy1, ix0) * (wy1 * wx0)[:, None]
        + gather(iy1, ix1) * (wy1 * wx1)[:, None]
    )
    return out


def affine_transform(image, theta, out_h, out_w):
    """Warp `image` by affine `theta` into an (out_h, out_w) output.

    With the identity theta this is a plain corner-aligned bilinear resize —
    the same trick the reference uses for all dataset-side resizing
    (lib/transformation.py:15-45 "AffineTnf" with theta=None).
    """
    grid = affine_grid(theta, out_h, out_w)
    return grid_sample(image, grid.astype(image.dtype))


def resize_bilinear(image, out_h, out_w):
    """Corner-aligned bilinear resize of an NCHW batch."""
    b = image.shape[0]
    return grid_sample(image, identity_grid(b, out_h, out_w).astype(image.dtype))
