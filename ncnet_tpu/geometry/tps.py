"""Thin-plate-spline (TPS) warps.

Math parity target: the reference TpsGridGen (geotnf/transformation.py:425-561):
a regular grid_size x grid_size lattice of control points on [-1, 1]^2, the
L^-1 system matrix of Bookstein's TPS, and the U(r) = r^2 log(r^2) radial
basis (with U(0) = 0 via the r^2 -> 1 substitution at
geotnf/transformation.py:475,541).

Design differences from the reference (TPU-first):
* the L^-1 matrix is precomputed once in numpy at construction and closed
  over as a constant — XLA constant-folds it into the compiled program;
* `tps_apply` is a pure function over arbitrarily-shaped point sets, used both
  for grid generation (vectorized over H*W pixels) and point warping
  (geotnf/point_tnf.py:24-32), so there is a single TPS code path.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _control_points(grid_size: int) -> np.ndarray:
    """Regular lattice of control points on [-1,1]^2, shape [N, 2] (x, y).

    Ordering parity: the reference builds P via
    `P_Y, P_X = np.meshgrid(axis_coords, axis_coords)` then flattens
    (geotnf/transformation.py:447-451), i.e. X varies slowest.
    """
    axis = np.linspace(-1, 1, grid_size)
    py, px = np.meshgrid(axis, axis)
    return np.stack([px.reshape(-1), py.reshape(-1)], axis=1)


def _l_inverse(points: np.ndarray, reg_factor: float = 0.0) -> np.ndarray:
    """Inverse of the TPS system matrix L for control points [N, 2]."""
    n = points.shape[0]
    x, y = points[:, 0:1], points[:, 1:2]
    d2 = (x - x.T) ** 2 + (y - y.T) ** 2
    d2 = np.where(d2 == 0, 1.0, d2)  # diagonal: U(0) = 0 via log(1)
    k = d2 * np.log(d2)
    if reg_factor != 0:
        k = k + np.eye(n) * reg_factor
    p = np.concatenate([np.ones((n, 1)), x, y], axis=1)
    top = np.concatenate([k, p], axis=1)
    bot = np.concatenate([p.T, np.zeros((3, 3))], axis=1)
    l_mat = np.concatenate([top, bot], axis=0)
    return np.linalg.inv(l_mat).astype(np.float32)


class TpsGrid:
    """TPS warp parameterized by control-point displacements.

    theta layout parity with the reference (geotnf/transformation.py:499-500):
    [b, 2N] with the first N entries the X coords of the warped control
    points, the last N the Y coords.
    """

    def __init__(self, grid_size: int = 3, reg_factor: float = 0.0):
        self.grid_size = grid_size
        self.n = grid_size * grid_size
        cp = _control_points(grid_size)
        self.control_points = jnp.asarray(cp)  # [N, 2]
        li = _l_inverse(cp, reg_factor)
        self.li_w = jnp.asarray(li[: self.n, : self.n])  # [N, N]
        self.li_a = jnp.asarray(li[self.n :, : self.n])  # [3, N]

    def apply(self, theta, points, batched=None):
        """Warp `points` ([..., 2] normalized (x, y)) by TPS params `theta`.

        Args:
          theta: [b, 2N] (or [b, N, 2]-reshapable) target control coords.
          points: [b, ..., 2] or [...,2] points to transform (broadcast over b).
          batched: whether `points` carries a leading batch dim. None infers
            it from the shape — ambiguous exactly when points.shape[0] == b
            for an unbatched rank>=3 point grid, so internal callers that
            know pass it explicitly.

        Returns:
          [b, ..., 2] warped points.
        """
        b = theta.shape[0]
        theta = theta.reshape(b, 2, self.n)  # [b, (x|y), N]
        q = jnp.swapaxes(theta, 1, 2)  # [b, N, 2]
        w = jnp.einsum("mn,bnk->bmk", self.li_w, q)  # [b, N, 2] nonlinear wts
        a = jnp.einsum("mn,bnk->bmk", self.li_a, q)  # [b, 3, 2] affine wts

        if points.shape[-1] != 2:
            raise ValueError("points must have trailing dim 2")
        if batched is None:
            batched = points.ndim >= 3 and points.shape[0] == b
        if batched:
            pts = points  # already batched [b, ..., 2]
        else:
            pts = jnp.broadcast_to(points, (b,) + points.shape)
        flat = pts.reshape(b, -1, 2)  # [b, M, 2]

        d2 = jnp.sum(
            (flat[:, :, None, :] - self.control_points[None, None, :, :]) ** 2,
            axis=-1,
        )  # [b, M, N]
        d2 = jnp.where(d2 == 0, 1.0, d2)
        u = d2 * jnp.log(d2)

        affine = (
            a[:, 0:1, :]
            + flat[:, :, 0:1] * a[:, 1:2, :]
            + flat[:, :, 1:2] * a[:, 2:3, :]
        )  # [b, M, 2]
        nonlin = jnp.einsum("bmn,bnk->bmk", u, w)  # [b, M, 2]
        out = affine + nonlin
        return out.reshape(pts.shape)

    def grid(self, theta, out_h: int, out_w: int):
        """Dense [b, out_h, out_w, 2] TPS sampling grid."""
        xs = jnp.linspace(-1.0, 1.0, out_w)
        ys = jnp.linspace(-1.0, 1.0, out_h)
        gx, gy = jnp.meshgrid(xs, ys)
        pts = jnp.stack([gx, gy], axis=-1)  # [H, W, 2]
        return self.apply(theta, pts, batched=False)


def tps_point_transform(theta, points, grid_size: int = 3, reg_factor: float = 0.0):
    """Warp [b, 2, n] point sets with TPS (parity: geotnf/point_tnf.py:24-32)."""
    tps = TpsGrid(grid_size=grid_size, reg_factor=reg_factor)
    pts = jnp.swapaxes(points, 1, 2)  # [b, n, 2]
    warped = tps.apply(theta, pts, batched=True)
    return jnp.swapaxes(warped, 1, 2)


def affine_point_transform(theta, points):
    """Warp [b, 2, n] points by [b, 2, 3] (or [b,6]) affine params.

    Parity: geotnf/point_tnf.py:34-38.
    """
    theta = theta.reshape(-1, 2, 3)
    return jnp.einsum("bij,bjn->bin", theta[:, :, :2], points) + theta[:, :, 2:3]
