"""Geometry engine: grids, sampling, TPS, point transforms, flow I/O."""

from .coords import (
    normalize_axis,
    unnormalize_axis,
    points_to_unit_coords,
    points_to_pixel_coords,
)
from .grid import (
    affine_grid,
    identity_grid,
    grid_sample,
    affine_transform,
    resize_bilinear,
)
from .tps import TpsGrid, tps_point_transform, affine_point_transform
from .transform import (
    make_sampling_grid,
    geometric_transform,
    compose_aff_tps_grid,
    composed_transform,
    symmetric_image_pad,
    synth_pair,
    synth_two_pair,
    synth_two_stage,
    synth_two_stage_two_pair,
)
from .flow_io import (
    read_flo_file,
    write_flo_file,
    flow_to_sampling_grid,
    sampling_grid_to_flow,
    warp_image_by_flow,
)

__all__ = [
    "normalize_axis",
    "unnormalize_axis",
    "points_to_unit_coords",
    "points_to_pixel_coords",
    "affine_grid",
    "identity_grid",
    "grid_sample",
    "affine_transform",
    "resize_bilinear",
    "TpsGrid",
    "tps_point_transform",
    "affine_point_transform",
    "make_sampling_grid",
    "geometric_transform",
    "compose_aff_tps_grid",
    "composed_transform",
    "symmetric_image_pad",
    "synth_pair",
    "synth_two_pair",
    "synth_two_stage",
    "synth_two_stage_two_pair",
    "read_flo_file",
    "write_flo_file",
    "flow_to_sampling_grid",
    "sampling_grid_to_flow",
    "warp_image_by_flow",
]
