"""Geometry engine: grids, sampling, TPS, point transforms, flow I/O."""

from .coords import (
    normalize_axis,
    unnormalize_axis,
    points_to_unit_coords,
    points_to_pixel_coords,
)
from .grid import (
    affine_grid,
    identity_grid,
    grid_sample,
    affine_transform,
    resize_bilinear,
)
from .tps import TpsGrid, tps_point_transform, affine_point_transform
from .flow_io import (
    read_flo_file,
    write_flo_file,
    flow_to_sampling_grid,
    sampling_grid_to_flow,
    warp_image_by_flow,
)

__all__ = [
    "normalize_axis",
    "unnormalize_axis",
    "points_to_unit_coords",
    "points_to_pixel_coords",
    "affine_grid",
    "identity_grid",
    "grid_sample",
    "affine_transform",
    "resize_bilinear",
    "TpsGrid",
    "tps_point_transform",
    "affine_point_transform",
    "read_flo_file",
    "write_flo_file",
    "flow_to_sampling_grid",
    "sampling_grid_to_flow",
    "warp_image_by_flow",
]
