"""Middlebury .flo flow I/O and flow <-> sampling-grid conversion.

Host-side (numpy) utilities; parity target is geotnf/flow.py:7-124 of the
reference tree, including the 1e10 out-of-bounds sentinel convention consumed
by the external TSS evaluation kit.
"""

from __future__ import annotations

import numpy as np

_FLO_MAGIC = 202021.25


def read_flo_file(filename: str) -> np.ndarray:
    """Read a Middlebury .flo file into an [h, w, 2] float32 array."""
    with open(filename, "rb") as f:
        magic = np.fromfile(f, np.float32, count=1)
        if magic.size == 0 or magic[0] != np.float32(_FLO_MAGIC):
            raise TypeError(f"{filename}: bad .flo magic number")
        w = int(np.fromfile(f, np.int32, count=1)[0])
        h = int(np.fromfile(f, np.int32, count=1)[0])
        data = np.fromfile(f, np.float32, count=2 * w * h)
    return data.reshape(h, w, 2)


def write_flo_file(flow: np.ndarray, filename: str) -> None:
    """Write an [h, w, 2] flow field in Middlebury .flo format."""
    flow = np.asarray(flow, dtype=np.float32)
    h, w = flow.shape[:2]
    with open(filename, "wb") as f:
        np.array([_FLO_MAGIC], dtype=np.float32).tofile(f)
        np.array([w], dtype=np.int32).tofile(f)
        np.array([h], dtype=np.int32).tofile(f)
        flow.tofile(f)


def _normalize_axis_np(x, length):
    return (x - 1 - (length - 1) / 2) * 2 / (length - 1)


def _unnormalize_axis_np(x, length):
    return x * (length - 1) / 2 + 1 + (length - 1) / 2


def flow_to_sampling_grid(flow: np.ndarray, h_src: int, w_src: int) -> np.ndarray:
    """Convert a target->source flow field to a normalized sampling grid.

    Parity: geotnf/flow.py:70-84. Pixel indices are 1-based (Matlab heritage
    of the TSS ground truth).
    """
    h_tgt, w_tgt = flow.shape[:2]
    gx, gy = np.meshgrid(np.arange(1, w_tgt + 1), np.arange(1, h_tgt + 1))
    sx = _normalize_axis_np(gx + flow[:, :, 0], w_src)
    sy = _normalize_axis_np(gy + flow[:, :, 1], h_src)
    return np.stack([sx, sy], axis=2).astype(np.float32)


def sampling_grid_to_flow(source_grid: np.ndarray, h_src: int, w_src: int) -> np.ndarray:
    """Convert a normalized [h_tgt, w_tgt, 2] sampling grid to a flow field.

    Out-of-bounds grid locations get the 1e10 sentinel expected by the TSS
    evaluation kit (parity: geotnf/flow.py:103-124).
    """
    source_grid = np.asarray(source_grid)
    if source_grid.ndim == 4:
        source_grid = source_grid[0]
    h_tgt, w_tgt = source_grid.shape[:2]
    sxn, syn = source_grid[:, :, 0], source_grid[:, :, 1]
    in_bounds = (sxn > -1) & (sxn < 1) & (syn > -1) & (syn < 1)
    sx = _unnormalize_axis_np(sxn, w_src)
    sy = _unnormalize_axis_np(syn, h_src)
    gx, gy = np.meshgrid(np.arange(1, w_tgt + 1), np.arange(1, h_tgt + 1))
    dx = (sx - gx) * in_bounds + 1e10 * (1 - in_bounds)
    dy = (sy - gy) * in_bounds + 1e10 * (1 - in_bounds)
    return np.stack([dx, dy], axis=2)


def warp_image_by_flow(image: np.ndarray, flow: np.ndarray) -> np.ndarray:
    """Warp an [h, w, c] uint8/float image by a target->source flow field."""
    # Local import: geometry.grid is jax; keep flow_io importable host-only.
    import jax.numpy as jnp

    from .grid import grid_sample

    grid = flow_to_sampling_grid(flow, image.shape[0], image.shape[1])
    img = jnp.asarray(image.astype(np.float32).transpose(2, 0, 1)[None])
    out = grid_sample(img, jnp.asarray(grid)[None])
    return np.asarray(out[0]).transpose(1, 2, 0).astype(np.uint8)
