"""Coordinate normalization between pixel and normalized [-1, 1] spaces.

Semantics match the reference implementation (see /root/reference
geotnf/point_tnf.py:6-10 and lib/point_tnf.py:6-10): pixel coordinates follow
the 1-indexed convention used by the PF-Pascal/PF-Willow Matlab annotations,
so pixel 1 maps to -1 and pixel L maps to +1.

All functions are pure jnp and jit-safe.
"""

from __future__ import annotations

import jax.numpy as jnp


def normalize_axis(x, length):
    """Map 1-indexed pixel coords [1, L] to normalized coords [-1, 1]."""
    length = jnp.asarray(length, dtype=jnp.result_type(x, jnp.float32))
    return (x - 1 - (length - 1) / 2) * 2 / (length - 1)


def unnormalize_axis(x, length):
    """Map normalized coords [-1, 1] back to 1-indexed pixel coords [1, L]."""
    length = jnp.asarray(length, dtype=jnp.result_type(x, jnp.float32))
    return x * (length - 1) / 2 + 1 + (length - 1) / 2


def points_to_unit_coords(points, im_size):
    """Normalize point sets from pixel to [-1, 1] coords.

    Args:
      points: [b, 2, n] array; row 0 is X, row 1 is Y (pixel coords).
      im_size: [b, 2] array of (height, width) per batch element.

    Returns:
      [b, 2, n] normalized points.

    Reference parity: lib/point_tnf.py:152-159 (X normalized by width,
    Y by height).
    """
    h = im_size[:, 0:1]
    w = im_size[:, 1:2]
    x = normalize_axis(points[:, 0, :], w)
    y = normalize_axis(points[:, 1, :], h)
    return jnp.stack([x, y], axis=1)


def points_to_pixel_coords(points, im_size):
    """Inverse of :func:`points_to_unit_coords` (lib/point_tnf.py:161-168)."""
    h = im_size[:, 0:1]
    w = im_size[:, 1:2]
    x = unnormalize_axis(points[:, 0, :], w)
    y = unnormalize_axis(points[:, 1, :], h)
    return jnp.stack([x, y], axis=1)
