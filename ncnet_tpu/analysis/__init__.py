"""Unified static-analysis pass over the ncnet_tpu codebase.

The repo grew three serving-critical concurrency layers (batcher
threads, fleet replicas, bulk-pipeline writers) and a family of ad-hoc
AST lints that each reimplemented file walking, AST visiting, and
reporting. This package is the shared home:

* :mod:`~ncnet_tpu.analysis.engine` — repo file discovery, per-file
  AST + line cache, the :class:`~ncnet_tpu.analysis.engine.Rule`
  protocol, :class:`~ncnet_tpu.analysis.engine.Finding` records,
  ``# ncnet-lint: disable=<rule>`` pragma and ``baseline.json``
  suppression.
* :mod:`~ncnet_tpu.analysis.rules` — the rule set: ``trace-purity``
  (host syncs inside jitted code), ``lock-order`` (deadlock-hazard
  cycles in the lock-acquisition graph), ``recompile-hazard``
  (unhashable / nondeterministic cache-key construction), and the
  ported docs cross-checks (``bare-print``, ``metrics-docs``,
  ``failpoint-docs``).

Run it via ``python tools/ncnet_lint.py`` (one-JSON-line contract,
nonzero exit on non-baselined findings) or the tier-1 test
``tests/test_analysis_engine.py``. Rule catalog, pragma grammar, and
the generated lock-acquisition-order table live in docs/ANALYSIS.md.
"""

from .engine import (  # noqa: F401
    Baseline,
    Finding,
    Report,
    Repo,
    Rule,
    run_rules,
)
from .rules import all_rules, get_rules  # noqa: F401

__all__ = [
    "Baseline",
    "Finding",
    "Report",
    "Repo",
    "Rule",
    "run_rules",
    "all_rules",
    "get_rules",
]
