"""Dynamic race canary: runtime assertions for ``# guarded-by:`` claims.

The ``shared-state-race`` rule (rules/races.py) is a static
under-approximation; annotations are where a human overrides it
("this field IS guarded by that lock", "only one thread writes
this"). This module keeps those claims honest: under
``NCNET_RACE_CANARY=1`` the pytest hook in tests/conftest.py calls
:func:`install_canaries`, which replaces every *annotated* instance
field with a data descriptor that asserts the annotation at each
write:

* ``guarded-by: <lock>`` (same-object locks only, e.g.
  ``Session.lock`` / ``self._lock``) — every write after the first
  (the constructor's) must happen while the lock is held. ``RLock`` /
  ``Condition`` expose ``_is_owned`` (held *by this thread*); a plain
  ``Lock`` only exposes ``locked()`` — weaker, but it still catches
  the lock-free write path.
* ``guarded-by: single-writer`` — the main-thread-handoff model:
  writes may come from the main thread until the first non-main
  writer appears; from then on only that one thread may write.

A violation raises :class:`RaceCanaryError` naming the field, the
writing thread, and the claimed guard — so the serving e2e / chaos
suites double as a cheap sanitizer pass. ``threading.local`` /
``atomic`` / ``external`` annotations and module globals carry no
runtime check. With the env var unset nothing is installed; the
production code path never imports this module.
"""

from __future__ import annotations

import importlib
import threading
import weakref
from typing import List, Optional

ENV_KNOB = "NCNET_RACE_CANARY"


class RaceCanaryError(AssertionError):
    """An annotated guard did not hold at a runtime write."""


def _lock_is_held(lock) -> bool:
    owned = getattr(lock, "_is_owned", None)
    if callable(owned):
        try:
            return bool(owned())
        except Exception:
            pass
    locked = getattr(lock, "locked", None)
    if callable(locked):
        try:
            return bool(locked())
        except Exception:
            pass
    # Unrecognized lock object: nothing cheap to assert.
    return True


class _Canary:
    """Data descriptor asserting a field's guarded-by claim per write.

    The value lives in the instance ``__dict__`` under a private slot
    key, so the descriptor (a *data* descriptor — it defines
    ``__set__``) keeps intercepting every store. The first write per
    instance is the constructor's and is exempt — ``__init__`` /
    dataclass field defaults run before the guard can exist.
    """

    def __init__(self, cls_name: str, attr: str, kind: str,
                 lock_attr: Optional[str] = None):
        self.cls_name = cls_name
        self.attr = attr
        self.kind = kind          # "lock" | "single-writer"
        self.lock_attr = lock_attr
        self._slot = f"__canary_{attr}"
        self._writer_slot = f"__canary_writer_{attr}"

    def __set_name__(self, owner, name):  # pragma: no cover - trivial
        self.attr = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            return obj.__dict__[self._slot]
        except KeyError:
            raise AttributeError(
                f"{self.cls_name}.{self.attr}") from None

    def __set__(self, obj, value):
        first = self._slot not in obj.__dict__
        if not first:
            self._check(obj)
        obj.__dict__[self._slot] = value

    def __delete__(self, obj):
        obj.__dict__.pop(self._slot, None)
        obj.__dict__.pop(self._writer_slot, None)

    def _check(self, obj) -> None:
        if self.kind == "lock":
            lock = getattr(obj, self.lock_attr, None)
            if lock is not None and not _lock_is_held(lock):
                raise RaceCanaryError(
                    f"{self.cls_name}.{self.attr} written by thread "
                    f"{threading.current_thread().name!r} without "
                    f"holding the annotated guard "
                    f"{self.cls_name}.{self.lock_attr}"
                )
        elif self.kind == "single-writer":
            me = threading.current_thread()
            if me is threading.main_thread():
                owner = obj.__dict__.get(self._writer_slot)
                if owner is not None:
                    raise RaceCanaryError(
                        f"{self.cls_name}.{self.attr} is annotated "
                        f"single-writer and was handed off to thread "
                        f"{owner[0]!r}, but the main thread wrote it "
                        f"again"
                    )
                return
            owner = obj.__dict__.get(self._writer_slot)
            if owner is None:
                # Identity is the Thread OBJECT (weakly held), not the
                # OS ident: idents are recycled as soon as a thread
                # exits, so an ident match would let a later thread
                # impersonate a dead owner. A dead weakref can never be
                # the current thread, which keeps ownership permanent.
                obj.__dict__[self._writer_slot] = (
                    me.name, weakref.ref(me))
            elif owner[1]() is not me:
                raise RaceCanaryError(
                    f"{self.cls_name}.{self.attr} is annotated "
                    f"single-writer (owner thread {owner[0]!r}) but "
                    f"thread {me.name!r} wrote it"
                )


def _module_name(rel: str) -> str:
    return rel[:-3].replace("/", ".")


def install_canaries(root: Optional[str] = None) -> List[str]:
    """Wrap every annotated instance field from the static plan.

    Imports each owning module and replaces the class attribute with a
    :class:`_Canary` descriptor. Idempotent (re-wrapping a descriptor
    is skipped). Returns the installed field labels, for logging and
    for the tests that assert the plan is non-trivial.
    """
    from .engine import Repo
    from .rules import races

    repo = Repo(root=root) if root else Repo()
    installed: List[str] = []
    for spec in races.canary_plan(repo):
        try:
            mod = importlib.import_module(_module_name(spec["module_rel"]))
            cls = getattr(mod, spec["cls"])
        except Exception:
            continue  # gated/optional module: nothing to wrap
        if isinstance(cls.__dict__.get(spec["attr"]), _Canary):
            installed.append(f"{spec['cls']}.{spec['attr']}")
            continue
        desc = _Canary(spec["cls"], spec["attr"], spec["kind"],
                       lock_attr=spec.get("lock_attr"))
        setattr(cls, spec["attr"], desc)
        installed.append(f"{spec['cls']}.{spec['attr']}")
    return installed
