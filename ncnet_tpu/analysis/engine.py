"""Shared AST-walking rule engine for the repo's static-analysis pass.

One parse per file per run: rules receive a :class:`Repo` whose
:class:`SourceFile` objects cache source text, line lists, and the
parsed AST, so adding a rule costs one more tree walk, not one more
disk+parse sweep (the pre-engine lints each re-walked the package).

Suppression has exactly two grammars, both deliberate-and-visible:

* **Pragma** — ``# ncnet-lint: disable=<rule>[,<rule>...]`` on the
  flagged line or the line directly above it silences those rules for
  that line; ``# ncnet-lint: disable-file=<rule>[,...]`` anywhere in a
  file's first 10 lines silences the whole file. A pragma in a
  function's *header* (the ``def`` line, any decorator line, or the
  line directly above the first decorator) suppresses findings
  attributed to that function — by symbol or by a line inside its
  body, the same symbol-or-line matching the baseline uses, so a
  pragma on a decorated ``def`` covers the whole def. ``disable=all``
  is accepted but discouraged — name the rule you mean.
* **Baseline** — ``ncnet_tpu/analysis/baseline.json`` carries
  deliberate, *commented* exceptions: every entry needs a nonempty
  ``reason`` (the tier-1 test enforces it). A finding matching a
  baseline entry still counts in ``findings`` but not in ``new``; only
  ``new`` findings fail the lint. The baseline is for exceptions, not
  for burying violations — fix the code or pragma it with a
  justification instead.

See docs/ANALYSIS.md for the rule catalog and how to add a rule.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Pragma grammar (docs/ANALYSIS.md): trailing comment on the flagged
#: line or alone on the line above it.
PRAGMA_RE = re.compile(
    r"#\s*ncnet-lint:\s*(disable(?:-file)?)\s*=\s*([a-z0-9_,\-\s]+)"
)

#: How deep a ``disable-file`` pragma may sit (a header pragma, not a
#: buried one).
_FILE_PRAGMA_LINES = 10


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a repo-relative file + line.

    ``symbol`` is an optional stable anchor (a function/lock/site name)
    baselines can match on so entries survive unrelated line churn.
    """

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    symbol: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "message": self.message}
        if self.symbol:
            d["symbol"] = self.symbol
        return d


class SourceFile:
    """One parsed file: text, split lines, AST, and pragma map — each
    computed once and cached for every rule that asks."""

    def __init__(self, root: str, rel: str):
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        self._text: Optional[str] = None
        self._lines: Optional[List[str]] = None
        self._tree: Optional[ast.AST] = None
        self._pragmas: Optional[Dict[int, set]] = None
        self._file_pragmas: Optional[set] = None
        self._def_spans: Optional[List[Tuple[str, set, int, int]]] = None

    @property
    def text(self) -> str:
        if self._text is None:
            with open(self.path, encoding="utf-8") as fh:
                self._text = fh.read()
        return self._text

    @property
    def lines(self) -> List[str]:
        if self._lines is None:
            self._lines = self.text.splitlines()
        return self._lines

    @property
    def tree(self) -> ast.AST:
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=self.path)
        return self._tree

    def _scan_pragmas(self) -> None:
        self._pragmas = {}
        self._file_pragmas = set()
        for i, line in enumerate(self.lines, start=1):
            if "ncnet-lint" not in line:
                continue
            m = PRAGMA_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                if i <= _FILE_PRAGMA_LINES:
                    self._file_pragmas |= rules
            else:
                self._pragmas.setdefault(i, set()).update(rules)

    def disabled_rules(self, line: int) -> set:
        """Rules pragma-disabled at ``line`` (same line, the line
        above, or file-wide)."""
        if self._pragmas is None:
            self._scan_pragmas()
        out = set(self._file_pragmas or ())
        out |= self._pragmas.get(line, set())
        out |= self._pragmas.get(line - 1, set())
        return out

    def _scan_defs(self) -> None:
        """Index every def's header lines + body span for pragma
        matching (``_header_disabled``)."""
        self._def_spans = []
        try:
            tree = self.tree
        except (OSError, SyntaxError):
            return
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            header = {d.lineno for d in node.decorator_list}
            header.add(node.lineno)
            first = min(header)
            header.add(first - 1)
            self._def_spans.append(
                (node.name, header, first,
                 node.end_lineno or node.lineno))

    def _header_disabled(self, finding: Finding) -> set:
        """Rules disabled by a pragma in the header of a def the
        finding belongs to — matched by symbol (its leaf name) or by a
        line inside the def's body, mirroring the baseline's
        symbol-or-line matching so a pragma on a decorator line covers
        findings attributed to the decorated def."""
        if self._def_spans is None:
            self._scan_defs()
        leaf = finding.symbol.rsplit(".", 1)[-1] if finding.symbol else ""
        out: set = set()
        for name, header, start, end in self._def_spans:
            if name != leaf and not (start <= finding.line <= end):
                continue
            for ln in header:
                out |= (self._pragmas or {}).get(ln, set())
        return out

    def suppresses(self, finding: Finding) -> bool:
        disabled = self.disabled_rules(finding.line)
        if "all" in disabled or finding.rule in disabled:
            return True
        disabled = self._header_disabled(finding)
        return "all" in disabled or finding.rule in disabled


class Repo:
    """File discovery + per-file cache over the ``ncnet_tpu`` package.

    ``files()`` is the full library file set (every ``*.py`` under
    ``<root>/ncnet_tpu``, ``__pycache__`` excluded); ``selected()`` is
    the subset per-file rules should lint — the lint CLI's
    ``--changed-only`` narrows it while repo-wide cross-check rules
    (docs tables, the lock graph) keep reading ``files()`` so a partial
    file set can never fake a stale-docs or broken-graph verdict.
    """

    PKG = "ncnet_tpu"

    def __init__(self, root: Optional[str] = None,
                 selected: Optional[Sequence[str]] = None):
        if root is None:
            import ncnet_tpu

            root = os.path.dirname(
                os.path.dirname(os.path.abspath(ncnet_tpu.__file__)))
        self.root = os.path.abspath(root)
        self._cache: Dict[str, SourceFile] = {}
        self._all: Optional[List[str]] = None
        self._selected = (None if selected is None else
                          [p.replace(os.sep, "/") for p in selected])

    def _discover(self) -> List[str]:
        if self._all is None:
            out = []
            pkg_dir = os.path.join(self.root, self.PKG)
            for dirpath, dirs, names in os.walk(pkg_dir):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for fn in sorted(names):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), self.root)
                        out.append(rel.replace(os.sep, "/"))
            self._all = sorted(out)
        return self._all

    def file(self, rel: str) -> SourceFile:
        rel = rel.replace(os.sep, "/")
        sf = self._cache.get(rel)
        if sf is None:
            sf = self._cache[rel] = SourceFile(self.root, rel)
        return sf

    def files(self, under: Tuple[str, ...] = ()) -> List[SourceFile]:
        """Every library file, optionally filtered to repo-relative
        prefixes (e.g. ``("ncnet_tpu/serving/",)``)."""
        rels = self._discover()
        if under:
            rels = [r for r in rels if r.startswith(tuple(under))]
        return [self.file(r) for r in rels]

    def selected(self, under: Tuple[str, ...] = ()) -> List[SourceFile]:
        """The per-file-rule lint set: ``files()`` unless a selection
        (``--changed-only``) narrows it."""
        out = self.files(under)
        if self._selected is None:
            return out
        keep = set(self._selected)
        return [f for f in out if f.rel in keep]

    def read_doc(self, rel: str) -> Optional[str]:
        """A non-Python repo file's text (docs cross-checks), or None."""
        path = os.path.join(self.root, rel)
        try:
            with open(path, encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None


class Rule:
    """Protocol every rule implements.

    ``full_repo`` rules reason about cross-file invariants (docs
    tables, the lock graph) and always see the whole file set;
    per-file rules iterate ``repo.selected()`` so ``--changed-only``
    applies. ``check`` yields raw findings; pragma/baseline filtering
    is the engine's job, not the rule's.
    """

    rule_id: str = ""
    description: str = ""
    full_repo: bool = False

    def check(self, repo: Repo) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class Baseline:
    """``baseline.json``: deliberate, commented exceptions.

    Grammar (docs/ANALYSIS.md)::

        {"version": 1, "entries": [
          {"rule": "trace-purity", "path": "ncnet_tpu/x.py",
           "line": 12, "symbol": "f", "reason": "why this is OK"}]}

    Matching: ``rule`` and ``path`` must equal the finding's; then
    ``symbol`` (when the entry carries one) or ``line`` anchors it.
    Symbol matches survive line churn; line matches are for findings
    with no stable symbol.
    """

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = list(entries or [])

    @classmethod
    def default_path(cls, repo: Repo) -> str:
        return os.path.join(repo.root, Repo.PKG, "analysis",
                            "baseline.json")

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return cls([])
        return cls(data.get("entries", []))

    def save(self, path: str) -> None:
        data = {"version": 1, "entries": self.entries}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=True)
            fh.write("\n")

    def matches(self, finding: Finding) -> bool:
        for e in self.entries:
            if e.get("rule") != finding.rule:
                continue
            if e.get("path") != finding.path:
                continue
            if e.get("symbol"):
                if e["symbol"] == finding.symbol:
                    return True
                continue
            if e.get("line") == finding.line:
                return True
        return False

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        entries = []
        for f in findings:
            e = {"rule": f.rule, "path": f.path, "line": f.line,
                 "reason": ""}
            if f.symbol:
                e["symbol"] = f.symbol
            entries.append(e)
        return cls(entries)


@dataclass
class Report:
    """One engine run: what was found, what suppressed it."""

    findings: List[Finding] = field(default_factory=list)  # non-pragma'd
    new: List[Finding] = field(default_factory=list)  # not baselined
    suppressed: int = 0  # pragma-silenced
    rules: List[str] = field(default_factory=list)
    files: int = 0

    @property
    def ok(self) -> bool:
        return not self.new

    def to_dict(self) -> dict:
        return {
            "findings": len(self.findings),
            "new": len(self.new),
            "rules": list(self.rules),
            "suppressed": self.suppressed,
            "files": self.files,
        }


def run_rules(repo: Repo, rules: Sequence[Rule],
              baseline: Optional[Baseline] = None) -> Report:
    """Run ``rules`` over ``repo``; pragma-filter, then baseline-split.

    Findings pointing into files the repo can parse get pragma
    filtering; findings anchored elsewhere (docs files) never do —
    docs rows are fixed in the docs, not pragma'd.
    """
    baseline = baseline or Baseline([])
    report = Report(rules=[r.rule_id for r in rules],
                    files=len(repo.selected()))
    for rule in rules:
        for finding in rule.check(repo):
            if finding.path.endswith(".py"):
                try:
                    if repo.file(finding.path).suppresses(finding):
                        report.suppressed += 1
                        continue
                except OSError:
                    pass
            report.findings.append(finding)
            if not baseline.matches(finding):
                report.new.append(finding)
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    report.new.sort(key=lambda f: (f.path, f.line, f.rule))
    return report


# -- shared AST helpers (used by several rules) ---------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """The dotted name a call targets, or None for computed callees."""
    return dotted_name(node.func)
