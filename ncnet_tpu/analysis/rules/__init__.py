"""Rule registry: every first-class rule, by stable id.

Adding a rule (docs/ANALYSIS.md "Adding a rule"): implement the
:class:`~ncnet_tpu.analysis.engine.Rule` protocol in a module here,
register it in :data:`_RULES`, document it in the docs catalog, and
seed a known-bad fixture in tests/test_analysis_engine.py.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..engine import Rule
from .bare_print import BarePrintRule
from .failpoint_docs import FailpointDocsRule
from .lock_order import LockOrderRule
from .metrics_docs import MetricsDocsRule
from .races import SharedStateRaceRule
from .recompile_hazard import RecompileHazardRule
from .trace_purity import TracePurityRule

_RULES = (
    TracePurityRule,
    LockOrderRule,
    SharedStateRaceRule,
    RecompileHazardRule,
    BarePrintRule,
    MetricsDocsRule,
    FailpointDocsRule,
)


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULES]


def rule_ids() -> List[str]:
    return [cls.rule_id for cls in _RULES]


def get_rules(ids: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate the named rules (all, when ``ids`` is falsy)."""
    if not ids:
        return all_rules()
    by_id = {cls.rule_id: cls for cls in _RULES}
    out = []
    for rid in ids:
        if rid not in by_id:
            raise KeyError(
                f"unknown rule {rid!r}; known: {sorted(by_id)}")
        out.append(by_id[rid]())
    return out
