"""``trace-purity``: no host syncs or impure calls inside traced code.

A host sync hiding inside a jitted hot path (``float()`` on a traced
array, ``np.asarray``, ``jax.device_get``, ``.item()``) either crashes
at trace time or — worse — silently forces a device round-trip per
step that PR 4's compile telemetry can only observe *after* it ships.
Impure calls (``time.*``, ``print``, host RNG) trace to a constant or
interleave with the XLA program in ways no test pins. GSPMD-era JAX
systems live or die on static-shape, sync-free traced code
(arXiv:2105.04663) — so this rule enforces it statically.

What counts as *traced code*:

* a function decorated ``@jax.jit`` / ``@jit`` /
  ``@(functools.)partial(jax.jit, ...)``;
* a function passed to ``jax.jit(f)`` / ``jit(f)``;
* a function (or lambda) passed as the body of ``(jax.)lax.scan`` /
  ``(jax.)lax.map`` — including scans *inside* an already-traced
  function;
* everything lexically nested inside the above;
* **one level** of call-graph resolution within the same module: a
  traced function calling module-local helper ``f()`` gets ``f``'s
  body scanned too (cross-module calls are out of scope — the module
  boundary is where shape/purity contracts are documented).

Flagged calls: ``float()``, ``.item()``, ``.tolist()``,
``np.asarray``/``np.array``, ``jax.device_get``,
``.block_until_ready()``, ``time.*``, bare ``print``, and host RNG
(``np.random.*``, stdlib ``random.*``, ``os.urandom``, ``uuid.*``).
``jax.random.*`` is pure and exempt. Deliberate exceptions get a
one-line ``# ncnet-lint: disable=trace-purity`` with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..engine import Finding, Repo, Rule, dotted_name

#: Exact dotted callees that sync or break purity inside a trace.
_BANNED_EXACT = {
    "float": "host conversion of a traced value",
    "print": "host I/O inside traced code",
    "np.asarray": "forces a device->host transfer",
    "np.array": "forces a device->host transfer",
    "numpy.asarray": "forces a device->host transfer",
    "numpy.array": "forces a device->host transfer",
    "jax.device_get": "explicit device->host fetch",
    "os.urandom": "host RNG traces to a constant",
}

#: Dotted-prefix callees (module families) banned inside a trace.
_BANNED_PREFIXES = {
    "time.": "wall-clock reads trace to a constant",
    "np.random.": "host RNG traces to a constant (use jax.random)",
    "numpy.random.": "host RNG traces to a constant (use jax.random)",
    "random.": "host RNG traces to a constant (use jax.random)",
    "uuid.": "host RNG traces to a constant",
}

#: Banned method calls on any object (attribute name alone).
_BANNED_METHODS = {
    "item": "syncs one element to the host",
    "tolist": "syncs the whole array to the host",
    "block_until_ready": "host sync inside traced code",
    "device_get": "explicit device->host fetch",
}

#: Function-position argument index for trace-body-taking callees.
_BODY_TAKERS = {
    "lax.scan": 0, "jax.lax.scan": 0,
    "lax.map": 0, "jax.lax.map": 0,
    "lax.fori_loop": 2, "jax.lax.fori_loop": 2,
    "lax.while_loop": 1, "jax.lax.while_loop": 1,
}


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``(functools.)partial(jax.jit, ...)``."""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


class _ModuleIndex:
    """Per-module maps the scanner needs: every function def by name
    (for one-level resolution) and the set of traced roots."""

    def __init__(self, tree: ast.AST):
        self.funcs: Dict[str, ast.AST] = {}
        self.traced: List[Tuple[ast.AST, str]] = []  # (func node, why)
        traced_ids: Set[int] = set()

        def mark(node: ast.AST, why: str) -> None:
            if node is not None and id(node) not in traced_ids:
                traced_ids.add(id(node))
                self.traced.append((node, why))

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Last def wins on name collisions; good enough for
                # one-level resolution of module-local helpers.
                self.funcs[node.name] = node
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        mark(node, f"@jit {node.name}")
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                if fn in ("jax.jit", "jit") and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        self._defer = getattr(self, "_defer", [])
                        self._defer.append((arg.id, f"jit({arg.id})"))
                    elif isinstance(arg, ast.Lambda):
                        mark(arg, "jit(lambda)")
                body_pos = _BODY_TAKERS.get(fn or "")
                if body_pos is not None and len(node.args) > body_pos:
                    arg = node.args[body_pos]
                    if isinstance(arg, ast.Name):
                        self._defer = getattr(self, "_defer", [])
                        self._defer.append((arg.id, f"{fn}({arg.id})"))
                    elif isinstance(arg, ast.Lambda):
                        mark(arg, f"{fn}(lambda)")
        # Name references resolve after the full def map exists (a body
        # may be defined after — or before — the site that traces it).
        for name, why in getattr(self, "_defer", []):
            fn_node = self.funcs.get(name)
            if fn_node is not None:
                mark(fn_node, why)


def _scan_body(func: ast.AST, index: _ModuleIndex, resolve: bool,
               seen_funcs: Set[int]) -> Iterable[Tuple[ast.Call, str]]:
    """Yield (banned call node, why) inside one traced function body.

    ``resolve``: follow one level of bare-name calls to module-local
    defs. ``seen_funcs`` stops revisits (recursion, diamond calls).
    """
    if id(func) in seen_funcs:
        return
    seen_funcs.add(id(func))
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn is not None:
            if fn in _BANNED_EXACT:
                yield node, f"{fn}(): {_BANNED_EXACT[fn]}"
                continue
            hit = next((p for p in _BANNED_PREFIXES if fn.startswith(p)),
                       None)
            if hit is not None:
                yield node, f"{fn}(): {_BANNED_PREFIXES[hit]}"
                continue
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            # Skip dotted module calls already vetted above (e.g.
            # jax.random.split) — only flag *method* names when the
            # full dotted form wasn't a known-pure module path.
            if meth in _BANNED_METHODS and not (
                    fn and (fn.startswith("jax.random.")
                            or fn.startswith("jnp."))):
                yield node, f".{meth}(): {_BANNED_METHODS[meth]}"
                continue
        if resolve and isinstance(node.func, ast.Name):
            callee = index.funcs.get(node.func.id)
            if callee is not None:
                for call, why in _scan_body(callee, index, resolve=False,
                                            seen_funcs=seen_funcs):
                    # Attribute the finding to the impure line itself;
                    # the message names the traced entry it is reached
                    # from via this call.
                    yield call, why + f" (reached via {node.func.id}())"


class TracePurityRule(Rule):
    rule_id = "trace-purity"
    description = ("host-sync / impure calls inside jax.jit, lax.scan, "
                   "and lax.map bodies (one-level module-local call "
                   "resolution)")

    def check(self, repo: Repo) -> Iterable[Finding]:
        for sf in repo.selected():
            try:
                index = _ModuleIndex(sf.tree)
            except SyntaxError as exc:
                yield Finding(self.rule_id, sf.rel, exc.lineno or 1,
                              f"unparseable file: {exc.msg}")
                continue
            reported: Set[Tuple[int, str]] = set()
            for func, why in index.traced:
                seen: Set[int] = set()
                # The traced set is walked per root; nested defs inside
                # this root are covered by ast.walk, other roots get
                # their own pass (seen_funcs is per-root so a shared
                # helper is attributed from each trace reaching it).
                for call, reason in _scan_body(func, index, resolve=True,
                                               seen_funcs=seen):
                    key = (call.lineno, reason)
                    if key in reported:
                        continue
                    reported.add(key)
                    name = getattr(func, "name", "<lambda>")
                    yield Finding(
                        self.rule_id, sf.rel, call.lineno,
                        f"impure call in traced code ({why}): {reason}",
                        symbol=name,
                    )
