"""``shared-state-race``: unguarded cross-thread shared mutable state.

PR 13's headline bug — the backbone's channels-last trace flag was a
module global, silently corrupting concurrent replica-thread traces —
is the bug class the lock-order rule cannot see: it reasons about locks
that *exist*, not shared state that has *no* lock. This rule closes the
gap with RacerD-flavored ownership + lock-set reasoning over the same
AST index the lock-order rule builds:

* **Thread roots** — functions that run concurrently, discovered by
  AST: ``threading.Thread(target=...)`` / ``threading.Timer``, calls
  through *spawner* helpers (a function that hands one of its own
  parameters to ``Thread`` — the shadow sampler's ``_spawn``),
  ``executor.submit(fn, ...)`` / ``future.add_done_callback(fn)``, and
  HTTP handler methods (``do_*`` handler classes, plus every
  ``handle_*`` / ``healthz`` method of a class that constructs a
  ``ThreadingHTTPServer``). HTTP and executor roots are
  *self-concurrent* (two requests run the same handler at once);
  dedicated threads and timers are one thread each.
* **Shared-state inventory** — module-level mutable globals written
  from function bodies (``global`` rebinds, subscript stores, mutator
  calls; ``threading.local()`` values, locks, and module-init-only
  constants are excluded) and instance attributes of the lock-order
  class set whose accessors are reachable from two concurrent contexts
  (roots, counted with self-concurrency, plus "main" when an accessor
  is not reachable from any root). Module globals are *always* treated
  as shared: jit tracing and closures break static call chains (the PR
  13 flag was only reachable through a traced function), so requiring
  root-reachability would miss exactly the motivating bug.
* **Guarded-by inference** — a must-held-lock analysis reusing the
  lock-order acquisition data: a root enters with no locks; a callee's
  entry set is the intersection over known call sites of (caller entry
  ∪ locks lexically held at the site); a write's effective guard is its
  lexical held set ∪ the entry set. A field is guarded when the
  intersection over all its non-``__init__`` write sites is nonempty.
  Unguarded (or inconsistently guarded) writes to shared state are
  findings, as are check-then-act pairs (an ``if`` that reads a shared
  field with no lock held and writes it in the body — the double-init
  idiom that still races when only the write is locked).
* **Annotations** — ``# guarded-by: <guard>[ -- <justification>]`` on
  the field's defining line (or the line above) resolves a field
  deliberately. ``<guard>`` is a lock (``self._lock``, ``Class.attr``,
  ``modlock``) cross-checked against the known lock set, or one of the
  lock-free disciplines ``threading.local`` / ``single-writer`` /
  ``atomic`` / ``external`` — the lock-free kinds *require* the
  ``-- justification`` text. Annotated fields are exempt from findings
  and feed the dynamic race canary (``ncnet_tpu/analysis/canary.py``),
  which asserts at runtime, under ``NCNET_RACE_CANARY=1``, that the
  annotated guard actually holds at every write.

The shared-state inventory table is emitted into docs/ANALYSIS.md
between generated-block markers; like the lock-order table, this rule
fails the lint when the block is stale (``tools/ncnet_lint.py
--write-docs`` regenerates both).

Like the lock graph, everything here under-approximates runtime
behavior (unresolved calls contribute no reachability and no guards),
which is why scope is held to the concurrency-bearing trees plus
``models/`` and ``ops/`` — the trees replica threads trace through.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field as dc_field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..engine import Finding, Repo, Rule, dotted_name
from . import lock_order
from .lock_order import _Analyzer, _Class, _Module

#: The lock-order trees plus the model/op code replica threads trace
#: through (the PR 13 flag lived in models/backbone.py).
SCOPE = lock_order.SCOPE + (
    "ncnet_tpu/models/",
    "ncnet_tpu/ops/",
)

DOC_PATH = "docs/ANALYSIS.md"
BEGIN_MARK = "<!-- BEGIN GENERATED: shared-state -->"
END_MARK = "<!-- END GENERATED: shared-state -->"

#: ``# guarded-by: <guard>[ -- <justification>]``
ANNOT_RE = re.compile(
    r"#\s*guarded-by:\s*(?P<guard>[A-Za-z_][\w.\-]*)"
    r"(?:\s*--\s*(?P<why>\S.*?))?\s*$"
)

#: Lock-free disciplines; all of them REQUIRE a justification.
_FREE_KINDS = ("threading.local", "single-writer", "atomic", "external")

#: Container mutations that count as writes.
_MUTATORS = {
    "append", "appendleft", "add", "update", "pop", "popitem", "clear",
    "extend", "extendleft", "insert", "remove", "discard", "setdefault",
}

_HTTP_SERVER_CTORS = {
    "ThreadingHTTPServer", "HTTPServer", "ThreadingTCPServer",
}
_HTTP_METHOD_PREFIXES = ("do_", "handle_")
_INIT_METHODS = ("__init__", "__post_init__")

#: Self-concurrent root kinds run the same entry point on two threads
#: at once; a dedicated thread/timer is one thread.
_ROOT_WEIGHT = {"http": 2, "executor": 2, "thread": 1, "timer": 1}


@dataclass
class _Annot:
    guard: str  # normalized guard text as written
    kind: str  # "lock" or one of _FREE_KINDS
    why: str
    rel: str
    line: int
    lock_node: str = ""  # resolved "Class.attr"/"mod.name" for kind=lock


@dataclass
class _Access:
    func: str  # function key ("rel::Class.meth" / "rel::fn")
    rel: str
    line: int
    held: frozenset
    write: bool
    init: bool  # write inside __init__/__post_init__


@dataclass
class _FieldInfo:
    key: Tuple[str, str, str]  # (kind, owner, name)
    def_rel: str = ""
    def_line: int = 0
    accesses: List[_Access] = dc_field(default_factory=list)
    annot: Optional[_Annot] = None
    contexts: Dict[str, str] = dc_field(default_factory=dict)  # root->kind
    main_context: bool = False
    guard: frozenset = frozenset()

    @property
    def label(self) -> str:
        return f"{self.key[1]}.{self.key[2]}"

    def weight(self) -> int:
        w = sum(_ROOT_WEIGHT.get(k, 1) for k in self.contexts.values())
        return w + (1 if self.main_context else 0)

    def writes(self) -> List[_Access]:
        return [a for a in self.accesses if a.write and not a.init]


class _Ctx:
    """Per-function walk context."""

    def __init__(self, key: str, mod: _Module, cls: Optional[_Class],
                 node: ast.AST):
        self.key = key
        self.mod = mod
        self.cls = cls
        self.node = node
        self.init = getattr(node, "name", "") in _INIT_METHODS
        self.params = {a.arg for a in node.args.args} if hasattr(
            node, "args") else set()
        self.globals_decl: Set[str] = set()
        self.local_stores: Set[str] = set()
        self.param_types: Dict[str, str] = {}
        for a in getattr(node, "args", None) and node.args.args or ():
            ann = a.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                self.param_types[a.arg] = ann.value.split(".")[-1]
            elif ann is not None:
                nm = dotted_name(ann)
                if nm:
                    self.param_types[a.arg] = nm.split(".")[-1]
        for sub in ast.walk(node):
            if isinstance(sub, ast.Global):
                self.globals_decl.update(sub.names)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                          ast.Store):
                self.local_stores.add(sub.id)


class _RaceAnalyzer(_Analyzer):
    """Extends the lock-order analyzer with access collection, thread
    roots, reachability, and the must-held-at-entry fixpoint."""

    def __init__(self, repo: Repo):
        super().__init__(repo, scope=SCOPE)
        self.fields: Dict[Tuple[str, str, str], _FieldInfo] = {}
        self.call_sites: Dict[str, List[Tuple[str, frozenset]]] = {}
        self.reach_calls: Dict[str, Set[str]] = {}
        self.roots: Dict[str, str] = {}  # func key -> kind
        self.spawners: Set[str] = set()
        self.cta: List[Tuple[Tuple[str, str, str], str, str, int,
                             frozenset]] = []
        self.entry: Dict[str, Optional[frozenset]] = {}
        #: module rel -> {name: (line, style)}; style "local" for
        #: threading.local values (excluded from the shared set).
        self.global_defs: Dict[str, Dict[str, Tuple[int, str]]] = {}
        self.attr_defs: Dict[Tuple[str, str], Tuple[str, int]] = {}
        #: (owner, name) pairs whose definition is a container literal
        #: or ctor — the only targets subscript/mutator writes hit.
        self.containers: Set[Tuple[str, str]] = set()
        self.race_findings: List[Finding] = []

    def analyze(self) -> None:
        self.build()  # lock-order passes: index, call graph, may-sets
        self._collect_defs()
        self._find_roots()
        self._collect_accesses()
        self._reachability()
        self._entry_fixpoint()
        self._assemble()

    # -- definitions ------------------------------------------------------

    def _collect_defs(self) -> None:
        for mod in self.modules.values():
            defs: Dict[str, Tuple[int, str]] = {}
            try:
                tree = self.repo.file(mod.rel).tree
            except (OSError, SyntaxError):
                continue
            for node in tree.body:
                tgts = []
                if isinstance(node, ast.Assign):
                    tgts = [t for t in node.targets
                            if isinstance(t, ast.Name)]
                elif (isinstance(node, ast.AnnAssign)
                      and isinstance(node.target, ast.Name)):
                    tgts = [node.target]
                for t in tgts:
                    if t.id.startswith("__") or t.id in mod.mod_locks:
                        continue
                    style = "plain"
                    val = node.value
                    if isinstance(val, ast.Call):
                        ctor = dotted_name(val.func) or ""
                        if ctor.split(".")[-1] == "local":
                            style = "local"
                    if _is_container_expr(val) or (
                            isinstance(node, ast.AnnAssign)
                            and _is_container_ann(node.annotation)):
                        self.containers.add((mod.rel, t.id))
                    defs.setdefault(t.id, (t.lineno, style))
            self.global_defs[mod.rel] = defs
            # Instance-attr definition lines: class-body AnnAssign
            # (dataclass fields), else first `self.X = ...` in __init__.
            for node in tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                for item in node.body:
                    if (isinstance(item, ast.AnnAssign)
                            and isinstance(item.target, ast.Name)):
                        self.attr_defs.setdefault(
                            (node.name, item.target.id),
                            (mod.rel, item.lineno))
                        if (_is_container_ann(item.annotation)
                                or _is_container_expr(item.value)):
                            self.containers.add(
                                (node.name, item.target.id))
                for item in node.body:
                    if not isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    if item.name not in _INIT_METHODS:
                        continue
                    for sub in ast.walk(item):
                        if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                            continue
                        tl = (sub.targets if isinstance(sub, ast.Assign)
                              else [sub.target])
                        for tgt in tl:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                self.attr_defs.setdefault(
                                    (node.name, tgt.attr),
                                    (mod.rel, tgt.lineno))
                                if _is_container_expr(sub.value):
                                    self.containers.add(
                                        (node.name, tgt.attr))

    # -- thread roots -----------------------------------------------------

    def _callable_targets(self, expr: ast.AST, mod: _Module,
                          cls: Optional[_Class]) -> List[str]:
        """Function keys a callable expression may run: ``self.m``,
        module functions, ``functools.partial(f, ..)``, and every call
        a lambda body makes (the sampler's ``lambda: self._compare(..)``
        idiom)."""
        if isinstance(expr, ast.Lambda):
            out: List[str] = []
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    out.extend(self._resolve_call(sub, mod, cls))
            return out
        if isinstance(expr, ast.Call):
            fn = dotted_name(expr.func) or ""
            if fn.split(".")[-1] == "partial" and expr.args:
                return self._callable_targets(expr.args[0], mod, cls)
            return []
        name = dotted_name(expr)
        if not name:
            return []
        parts = name.split(".")
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2 and parts[1] in cls.methods:
                return [f"{cls.rel}::{cls.name}.{parts[1]}"]
            if len(parts) == 3:
                owner = self._attr_class(cls, parts[1])
                if owner is not None and parts[2] in owner.methods:
                    return [f"{owner.rel}::{owner.name}.{parts[2]}"]
            return []
        if len(parts) == 1:
            if parts[0] in mod.funcs:
                return [f"{mod.rel}::{parts[0]}"]
            if parts[0] in mod.from_binds:
                src, orig = mod.from_binds[parts[0]]
                smod = self._module_by_path(src)
                if smod is not None:
                    return self._func_in_module(smod, orig, hop=False)
            return []
        if len(parts) == 2:
            target = mod.imports.get(parts[0])
            if target:
                tmod = self._module_by_path(target)
                if tmod is not None:
                    return self._func_in_module(tmod, parts[1])
        return []

    def _find_roots(self) -> None:
        pending: List[Tuple[List[str], List[str]]] = []
        for key, (mod, cls, node) in self.funcs.items():
            params = {a.arg for a in node.args.args} if hasattr(
                node, "args") else set()
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = dotted_name(sub.func) or ""
                last = fn.split(".")[-1]
                target_expr = None
                kind = ""
                if last in ("Thread", "Timer"):
                    for kw in sub.keywords:
                        if kw.arg in ("target", "function"):
                            target_expr = kw.value
                    if (target_expr is None and last == "Timer"
                            and len(sub.args) >= 2):
                        target_expr = sub.args[1]
                    kind = "thread" if last == "Thread" else "timer"
                elif (isinstance(sub.func, ast.Attribute)
                      and sub.func.attr in ("submit", "add_done_callback")
                      and sub.args):
                    target_expr = sub.args[0]
                    kind = "executor"
                if target_expr is None:
                    continue
                if (kind == "thread"
                        and isinstance(target_expr, ast.Name)
                        and target_expr.id in params):
                    # This function Thread()s one of its own params:
                    # it is a spawner, its callers pass the real root.
                    self.spawners.add(key)
                    continue
                for tgt in self._callable_targets(target_expr, mod, cls):
                    self.roots.setdefault(tgt, kind)
            # HTTP server owners: every handle_*/do_*/healthz method of
            # a class that constructs a ThreadingHTTPServer runs on
            # handler threads (the nested Handler delegates to them).
            if cls is not None and self._builds_http_server(node):
                for meth in cls.methods:
                    if (meth.startswith(_HTTP_METHOD_PREFIXES)
                            or meth == "healthz"):
                        self.roots.setdefault(
                            f"{cls.rel}::{cls.name}.{meth}", "http")
        # Plain handler classes (module-level do_GET/do_POST/...).
        for key, (mod, cls, node) in self.funcs.items():
            name = getattr(node, "name", "")
            if cls is not None and name.startswith("do_"):
                self.roots.setdefault(key, "http")
        # Calls through spawners: the callable argument is the root.
        for key, (mod, cls, node) in self.funcs.items():
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                callees = self._resolve_call(sub, mod, cls)
                if not any(c in self.spawners for c in callees):
                    continue
                for arg in list(sub.args) + [kw.value
                                             for kw in sub.keywords]:
                    for tgt in self._callable_targets(arg, mod, cls):
                        self.roots.setdefault(tgt, "executor")

    @staticmethod
    def _builds_http_server(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn = dotted_name(sub.func) or ""
                if fn.split(".")[-1] in _HTTP_SERVER_CTORS:
                    return True
        return False

    # -- access collection ------------------------------------------------

    def _field(self, key: Tuple[str, str, str]) -> _FieldInfo:
        fi = self.fields.get(key)
        if fi is None:
            fi = self.fields[key] = _FieldInfo(key=key)
        return fi

    def _global_key(self, name: str,
                    ctx: _Ctx) -> Optional[Tuple[str, str, str]]:
        defs = self.global_defs.get(ctx.mod.rel, {})
        if name not in defs:
            return None
        if defs[name][1] == "local":  # threading.local: per-thread
            return None
        if name not in ctx.globals_decl and (
                name in ctx.params or name in ctx.local_stores):
            return None  # shadowed by a local/param
        return ("global", ctx.mod.rel, name)

    def _attr_key(self, dotted: str,
                  ctx: _Ctx) -> Optional[Tuple[str, str, str]]:
        parts = dotted.split(".")
        if len(parts) == 2:
            base, attr = parts
            owner: Optional[_Class] = None
            if base == "self":
                owner = ctx.cls
            elif base in ctx.param_types:
                owner = self.class_index.get(ctx.param_types[base])
            if owner is not None and attr not in owner.attr_locks:
                return ("attr", owner.name, attr)
            return None
        if len(parts) == 3 and parts[0] == "self" and ctx.cls is not None:
            owner = self._attr_class(ctx.cls, parts[1])
            if owner is not None and parts[2] not in owner.attr_locks:
                return ("attr", owner.name, parts[2])
        return None

    def _record(self, key: Optional[Tuple[str, str, str]], line: int,
                held: Tuple[str, ...], ctx: _Ctx, write: bool) -> None:
        if key is None:
            return
        self._field(key).accesses.append(_Access(
            func=ctx.key, rel=ctx.mod.rel, line=line,
            held=frozenset(held), write=write,
            init=ctx.init and write and key[0] == "attr"))

    def _is_container(self, key: Tuple[str, str, str]) -> bool:
        return (key[1], key[2]) in self.containers

    def _store_keys(self, tgt: ast.AST, ctx: _Ctx,
                    through_sub: bool = False
                    ) -> List[Tuple[str, str, str]]:
        out: List[Tuple[str, str, str]] = []
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                out.extend(self._store_keys(el, ctx, through_sub))
            return out
        if isinstance(tgt, ast.Starred):
            return self._store_keys(tgt.value, ctx, through_sub)
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value  # X[...] = v mutates X
            through_sub = True
        if isinstance(tgt, ast.Name):
            # A bare `X = v` without `global X` is a local bind, not a
            # global write; subscript/mutator forms reach the global —
            # but only when the definition really is a container.
            if tgt.id in ctx.globals_decl or not _is_plain_store(tgt):
                gk = self._global_key(tgt.id, ctx)
                if gk is not None and not (
                        through_sub and not self._is_container(gk)):
                    out.append(gk)
            return out
        name = dotted_name(tgt)
        if name:
            ak = self._attr_key(name, ctx)
            if ak is not None and not (
                    through_sub and not self._is_container(ak)):
                out.append(ak)
        return out

    def _collect_accesses(self) -> None:
        for key, (mod, cls, node) in self.funcs.items():
            ctx = _Ctx(key, mod, cls, node)
            for stmt in getattr(node, "body", ()):
                self._walk_access(stmt, (), ctx)

    def _walk_access(self, node: ast.AST, held: Tuple[str, ...],
                     ctx: _Ctx) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                lk = self._lock_of(item.context_expr, ctx.mod, ctx.cls)
                if lk:
                    acquired.append(lk)
                else:
                    self._walk_access(item.context_expr, held, ctx)
            inner = held + tuple(acquired)
            for stmt in node.body:
                self._walk_access(stmt, inner, ctx)
            return
        if isinstance(node, ast.If):
            self._check_then_act(node, held, ctx)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                for k in self._store_keys(tgt, ctx):
                    self._record(k, node.lineno, held, ctx, write=True)
        elif isinstance(node, ast.AugAssign):
            for k in self._store_keys(node.target, ctx):
                self._record(k, node.lineno, held, ctx, write=True)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            for k in self._store_keys(node.target, ctx):
                self._record(k, node.lineno, held, ctx, write=True)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                for k in self._store_keys(tgt, ctx):
                    self._record(k, node.lineno, held, ctx, write=True)
        elif isinstance(node, ast.Call):
            resolved = self._resolve_call(node, ctx.mod, ctx.cls)
            for tgt_key in resolved:
                self.call_sites.setdefault(tgt_key, []).append(
                    (ctx.key, frozenset(held)))
            fn = node.func
            # A resolvable call (`self.qos.update()`) is a method whose
            # body is analyzed directly — only unresolved attr calls
            # count as container mutations.
            if (not resolved and isinstance(fn, ast.Attribute)
                    and fn.attr in _MUTATORS):
                base = dotted_name(fn.value)
                if base:
                    k = (self._attr_key(base, ctx) if "." in base
                         else self._global_key(base, ctx))
                    if k is not None and self._is_container(k):
                        self._record(k, node.lineno, held, ctx,
                                     write=True)
        elif isinstance(node, ast.Attribute) and isinstance(node.ctx,
                                                            ast.Load):
            name = dotted_name(node)
            if name:
                self._record(self._attr_key(name, ctx), node.lineno,
                             held, ctx, write=False)
                # Property access across objects (healthz reading
                # `self.heartbeat.in_stall`): a call edge for
                # reachability, so the owner's fields see this context.
                parts = name.split(".")
                if (len(parts) == 3 and parts[0] == "self"
                        and ctx.cls is not None):
                    owner = self._attr_class(ctx.cls, parts[1])
                    if owner is not None and parts[2] in owner.methods:
                        self.calls[ctx.key].add(
                            f"{owner.rel}::{owner.name}.{parts[2]}")
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self._record(self._global_key(node.id, ctx), node.lineno,
                         held, ctx, write=False)
        for child in ast.iter_child_nodes(node):
            self._walk_access(child, held, ctx)

    def _check_then_act(self, node: ast.If, held: Tuple[str, ...],
                        ctx: _Ctx) -> None:
        if ctx.init:
            return
        read: Set[Tuple[str, str, str]] = set()
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Attribute) and isinstance(sub.ctx,
                                                             ast.Load):
                k = self._attr_key(dotted_name(sub) or "", ctx)
                if k:
                    read.add(k)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                          ast.Load):
                k = self._global_key(sub.id, ctx)
                if k:
                    read.add(k)
        if not read:
            return
        written: Set[Tuple[str, str, str]] = set()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        written.update(self._store_keys(tgt, ctx))
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    written.update(self._store_keys(sub.target, ctx))
                elif (isinstance(sub, ast.Call)
                      and isinstance(sub.func, ast.Attribute)
                      and sub.func.attr in _MUTATORS
                      and not self._resolve_call(sub, ctx.mod, ctx.cls)):
                    base = dotted_name(sub.func.value)
                    if base:
                        k = (self._attr_key(base, ctx) if "." in base
                             else self._global_key(base, ctx))
                        if k and self._is_container(k):
                            written.add(k)
        for k in sorted(read & written):
            self.cta.append((k, ctx.key, ctx.mod.rel, node.lineno,
                             frozenset(held)))

    # -- reachability + must-held entry -----------------------------------

    def _reachability(self) -> None:
        self.func_roots: Dict[str, Dict[str, str]] = {
            k: {} for k in self.funcs}
        for root, kind in self.roots.items():
            if root not in self.funcs:
                continue
            seen = {root}
            stack = [root]
            while stack:
                cur = stack.pop()
                self.func_roots.setdefault(cur, {})[root] = kind
                for nxt in self.calls.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)

    def _entry_fixpoint(self) -> None:
        # Optimistic must-analysis from TOP (None): entry(callee) =
        # ∩ over known call sites of (entry(caller) ∪ held-at-site);
        # roots enter bare. Functions with no known callers resolve to
        # the empty set — an unknown caller guarantees nothing.
        entry: Dict[str, Optional[frozenset]] = {
            k: None for k in self.funcs}
        for r in self.roots:
            if r in entry:
                entry[r] = frozenset()
        for _ in range(len(self.funcs)):
            changed = False
            for callee, sites in self.call_sites.items():
                if callee not in entry or entry.get(callee) == frozenset():
                    continue
                if callee in self.roots:
                    continue
                acc: Optional[frozenset] = None
                for caller, held in sites:
                    ce = entry.get(caller)
                    # An unknown caller (TOP) still guarantees what the
                    # site holds lexically — `_transition` called only
                    # inside `with self._lock:` blocks is guarded even
                    # when its callers' own entries never resolve.
                    val = held if ce is None else (ce | held)
                    acc = val if acc is None else (acc & val)
                if acc is not None and acc != entry[callee]:
                    entry[callee] = acc
                    changed = True
            if not changed:
                break
        self.entry = entry

    def _entry_of(self, func: str) -> frozenset:
        e = self.entry.get(func)
        return e if e is not None else frozenset()

    # -- assemble fields, annotations, findings ---------------------------

    def _definition_of(self, fi: _FieldInfo) -> Tuple[str, int]:
        kind, owner, name = fi.key
        if kind == "global":
            line, _style = self.global_defs.get(owner, {}).get(
                name, (0, "plain"))
            if line:
                return owner, line
        else:
            got = self.attr_defs.get((owner, name))
            if got:
                return got
        first = min(fi.accesses, key=lambda a: (a.rel, a.line),
                    default=None)
        return (first.rel, first.line) if first else ("", 0)

    def _parse_annotation(self, fi: _FieldInfo) -> Optional[_Annot]:
        rel, line = fi.def_rel, fi.def_line
        if not rel or not line:
            return None
        try:
            lines = self.repo.file(rel).lines
        except OSError:
            return None
        for ln in (line, line - 1):
            if not (1 <= ln <= len(lines)):
                continue
            m = ANNOT_RE.search(lines[ln - 1])
            if not m:
                continue
            guard = m.group("guard")
            why = (m.group("why") or "").strip()
            kind = "lock"
            if guard in _FREE_KINDS or (
                    guard == "threading.local"):
                kind = guard
            elif guard.split(".")[-1] == "local" and guard.startswith(
                    "threading"):
                kind = "threading.local"
            return _Annot(guard=guard, kind=kind, why=why, rel=rel,
                          line=ln)
        return None

    def _resolve_annot_lock(self, fi: _FieldInfo,
                            an: _Annot) -> Optional[str]:
        parts = an.guard.split(".")
        kind, owner_name, _ = fi.key
        if parts[0] == "self" and len(parts) == 2 and kind == "attr":
            owner = self.class_index.get(owner_name)
            if owner is not None and parts[1] in owner.attr_locks:
                return f"{owner_name}.{parts[1]}"
            return None
        if len(parts) == 2:
            owner = self.class_index.get(parts[0])
            if owner is not None and parts[1] in owner.attr_locks:
                return f"{parts[0]}.{parts[1]}"
            for mod in self.modules.values():
                if mod.base == parts[0] and parts[1] in mod.mod_locks:
                    return f"{mod.base}.{parts[1]}"
            return None
        if len(parts) == 1:
            for mod in self.modules.values():
                if kind == "global" and mod.rel != fi.key[1]:
                    continue
                if parts[0] in mod.mod_locks:
                    return f"{mod.base}.{parts[0]}"
        return None

    def _assemble(self) -> None:
        for fi in self.fields.values():
            for a in fi.accesses:
                roots = self.func_roots.get(a.func, {})
                if roots:
                    fi.contexts.update(roots)
                else:
                    fi.main_context = True
            fi.def_rel, fi.def_line = self._definition_of(fi)
            fi.annot = self._parse_annotation(fi)
            writes = fi.writes()
            if writes:
                guard = None
                for a in writes:
                    eff = a.held | self._entry_of(a.func)
                    guard = eff if guard is None else (guard & eff)
                fi.guard = guard or frozenset()
        self._emit_findings()

    def shared_fields(self) -> List[_FieldInfo]:
        """Inventory: function-written module globals, plus instance
        attrs written outside init and reachable from >= 2 concurrent
        contexts."""
        out = []
        for key in sorted(self.fields):
            fi = self.fields[key]
            if not fi.writes():
                continue
            if key[0] == "global" or fi.weight() >= 2:
                out.append(fi)
        return out

    def _ctx_summary(self, fi: _FieldInfo) -> str:
        counts: Dict[str, int] = {}
        for kind in fi.contexts.values():
            counts[kind] = counts.get(kind, 0) + 1
        parts = [f"{n} {k}" for k, n in sorted(counts.items())]
        if fi.main_context:
            parts.append("main")
        if fi.key[0] == "global":
            return "any trace/serving thread"
        return ", ".join(parts) if parts else "-"

    def _emit_findings(self) -> None:
        flagged: Set[Tuple[str, str, str]] = set()
        for fi in self.shared_fields():
            if fi.annot is not None:
                self._validate_annotation(fi)
                continue
            if fi.guard:
                continue
            writes = fi.writes()
            bare = [a for a in writes
                    if not (a.held | self._entry_of(a.func))]
            flagged.add(fi.key)
            what = ("module global" if fi.key[0] == "global"
                    else f"instance attr (contexts: "
                         f"{self._ctx_summary(fi)})")
            if bare:
                a = min(bare, key=lambda x: (x.rel, x.line))
                self.race_findings.append(Finding(
                    "shared-state-race", a.rel, a.line,
                    f"unguarded write to shared {what} {fi.label!r}: "
                    f"no dominating lock and no `# guarded-by:` "
                    f"annotation (add the lock, use threading.local, "
                    f"or annotate the definition at "
                    f"{fi.def_rel}:{fi.def_line})",
                    symbol=fi.label))
            else:
                a = min(writes, key=lambda x: (x.rel, x.line))
                locks = sorted({lk for w in writes
                                for lk in (w.held
                                           | self._entry_of(w.func))})
                self.race_findings.append(Finding(
                    "shared-state-race", a.rel, a.line,
                    f"inconsistently guarded writes to shared {what} "
                    f"{fi.label!r}: no single lock dominates "
                    f"(saw {', '.join(locks)}); pick one or annotate",
                    symbol=fi.label))
        for key, func, rel, line, held in self.cta:
            fi = self.fields.get(key)
            if fi is None or key in flagged or fi.annot is not None:
                continue
            if not fi.writes():
                continue
            if key[0] != "global" and fi.weight() < 2:
                continue
            if held | self._entry_of(func):
                continue
            self.race_findings.append(Finding(
                "shared-state-race", rel, line,
                f"check-then-act on shared state {fi.label!r}: the "
                f"test reads it with no lock held, the body writes it "
                f"- two threads can both pass the check (hold the "
                f"lock across the check, or annotate the definition)",
                symbol=fi.label))

    def _validate_annotation(self, fi: _FieldInfo) -> None:
        an = fi.annot
        assert an is not None
        if an.kind == "lock":
            node = self._resolve_annot_lock(fi, an)
            if node is None:
                self.race_findings.append(Finding(
                    "shared-state-race", an.rel, an.line,
                    f"`# guarded-by: {an.guard}` on {fi.label!r} names "
                    f"no known lock (known kinds: a lock attr/module "
                    f"lock, or {', '.join(_FREE_KINDS)})",
                    symbol=fi.label))
            else:
                an.lock_node = node
        elif not an.why:
            self.race_findings.append(Finding(
                "shared-state-race", an.rel, an.line,
                f"`# guarded-by: {an.kind}` on {fi.label!r} needs a "
                f"justification: `# guarded-by: {an.kind} -- <why "
                f"this lock-free discipline is safe>`",
                symbol=fi.label))


def _is_plain_store(tgt: ast.Name) -> bool:
    return isinstance(tgt.ctx, ast.Store)


_CONTAINER_CTORS = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter",
}
_CONTAINER_ANNS = {"dict", "list", "set", "Dict", "List", "Set",
                   "MutableMapping", "deque", "DefaultDict"}


def _is_container_expr(val: Optional[ast.AST]) -> bool:
    if isinstance(val, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp)):
        return True
    if isinstance(val, ast.Call):
        nm = (dotted_name(val.func) or "").split(".")[-1]
        return nm in _CONTAINER_CTORS
    return False


def _is_container_ann(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    nm = (dotted_name(ann) or "").split(".")[-1]
    return nm in _CONTAINER_ANNS


def analyze(repo: Repo) -> _RaceAnalyzer:
    an = _RaceAnalyzer(repo)
    an.analyze()
    return an


# -- generated docs block --------------------------------------------------


def _guard_text(fi: _FieldInfo) -> str:
    if fi.annot is not None:
        if fi.annot.kind == "lock":
            tgt = fi.annot.lock_node or fi.annot.guard
            return f"`{tgt}` (annotated)"
        return f"`{fi.annot.kind}` (annotated)"
    if fi.guard:
        return ", ".join(f"`{g}`" for g in sorted(fi.guard)) + " (inferred)"
    return "**UNGUARDED**"


def render_inventory_table(an: _RaceAnalyzer) -> str:
    lines = [
        "Generated by `python tools/ncnet_lint.py --write-docs` — do not",
        "edit by hand. Shared mutable state (module globals written from",
        "functions; instance attrs written outside `__init__` and",
        "reachable from two concurrent contexts) with the guard that",
        "protects each field — a lock the `shared-state-race` rule",
        "inferred from the write sites, or a `# guarded-by:` annotation",
        "at the definition.",
        "",
        "| Shared state | Kind | Defined at | Guard | Concurrent "
        "contexts |",
        "|---|---|---|---|---|",
    ]
    rows = []
    for fi in an.shared_fields():
        kind = "global" if fi.key[0] == "global" else "attr"
        label = (f"{fi.key[1].rsplit('/', 1)[-1][:-3]}.{fi.key[2]}"
                 if kind == "global" else fi.label)
        rows.append((label, kind, f"{fi.def_rel}:{fi.def_line}",
                     _guard_text(fi), self_ctx(an, fi)))
    for label, kind, where, guard, ctx in sorted(rows):
        lines.append(f"| `{label}` | {kind} | `{where}` | {guard} "
                     f"| {ctx} |")
    lines.append("")
    n_ann = sum(1 for fi in an.shared_fields() if fi.annot is not None)
    lines.append(f"{len(rows)} shared field(s); {n_ann} annotated, "
                 f"the rest lock-guarded by inference. The rule fails "
                 f"the lint when any row is unguarded or this table "
                 f"is stale.")
    return "\n".join(lines)


def self_ctx(an: _RaceAnalyzer, fi: _FieldInfo) -> str:
    return an._ctx_summary(fi)


def write_docs_block(repo: Repo) -> bool:
    """Rewrite the generated shared-state block in docs/ANALYSIS.md.

    Returns True when the file changed; prose outside the markers is
    untouched."""
    import os

    doc_path = os.path.join(repo.root, DOC_PATH)
    try:
        with open(doc_path, encoding="utf-8") as fh:
            doc = fh.read()
    except OSError:
        return False
    if BEGIN_MARK not in doc or END_MARK not in doc:
        return False
    head, rest = doc.split(BEGIN_MARK, 1)
    _stale, tail = rest.split(END_MARK, 1)
    table = render_inventory_table(analyze(repo))
    new = head + BEGIN_MARK + "\n" + table + "\n" + END_MARK + tail
    if new == doc:
        return False
    with open(doc_path, "w", encoding="utf-8") as fh:
        fh.write(new)
    return True


def canary_plan(repo: Repo) -> List[dict]:
    """Annotated instance fields the dynamic race canary can wrap:
    lock-annotated fields (assert the lock is held at every write) and
    single-writer fields (assert writes stay on one thread after the
    main-thread handoff). Other kinds (threading.local, atomic,
    external) and module globals carry no runtime check."""
    an = analyze(repo)
    plan: List[dict] = []
    for fi in an.fields.values():
        if fi.key[0] != "attr" or fi.annot is None:
            continue
        spec = {"module_rel": fi.def_rel, "cls": fi.key[1],
                "attr": fi.key[2], "kind": fi.annot.kind}
        if fi.annot.kind == "lock":
            node = fi.annot.lock_node or ""
            if not node or node.split(".")[0] != fi.key[1]:
                continue  # only same-object locks are checkable
            spec["lock_attr"] = node.split(".")[1]
        elif fi.annot.kind != "single-writer":
            continue
        plan.append(spec)
    plan.sort(key=lambda s: (s["cls"], s["attr"]))
    return plan


class SharedStateRaceRule(Rule):
    rule_id = "shared-state-race"
    description = ("unguarded writes / check-then-act races on shared "
                   "mutable state (module globals, multi-thread-root "
                   "instance attrs) across serving/, obs/, "
                   "reliability/, pipeline/, models/, ops/; "
                   "docs/ANALYSIS.md inventory freshness")
    full_repo = True  # reachability must never see a partial repo

    def check(self, repo: Repo) -> Iterable[Finding]:
        an = _RaceAnalyzer(repo)
        an.analyze()
        for f in an.findings:  # unparseable-file findings from build()
            yield Finding(self.rule_id, f.path, f.line, f.message,
                          f.symbol)
        yield from an.race_findings
        yield from self._check_docs(repo, an)

    def _check_docs(self, repo: Repo,
                    an: _RaceAnalyzer) -> Iterable[Finding]:
        doc = repo.read_doc(DOC_PATH)
        want = lock_order._normalize(render_inventory_table(an))
        if doc is None:
            yield Finding(self.rule_id, DOC_PATH, 1,
                          f"{DOC_PATH} is missing; run "
                          "`python tools/ncnet_lint.py --write-docs`",
                          symbol="docs-block")
            return
        if BEGIN_MARK not in doc or END_MARK not in doc:
            yield Finding(self.rule_id, DOC_PATH, 1,
                          f"{DOC_PATH} lacks the generated shared-state "
                          f"block markers ({BEGIN_MARK}); run "
                          "`python tools/ncnet_lint.py --write-docs`",
                          symbol="docs-block")
            return
        begin_line = doc[: doc.index(BEGIN_MARK)].count("\n") + 1
        body = doc.split(BEGIN_MARK, 1)[1].split(END_MARK, 1)[0]
        if lock_order._normalize(body) != want:
            yield Finding(self.rule_id, DOC_PATH, begin_line,
                          "generated shared-state inventory table is "
                          "stale; run "
                          "`python tools/ncnet_lint.py --write-docs`",
                          symbol="docs-block")
