"""``bare-print``: no bare ``print()`` in library code.

Library modules under ``ncnet_tpu/`` (everything except ``cli/``, which
IS the user-facing stdout surface) must report through the structured
run log (``ncnet_tpu.obs``) or an explicit stream (``file=sys.stderr``),
never bare ``print()``: library stdout interleaves with machine-read
contracts like bench.py's single headline JSON line and is invisible to
tools/obs_report.py.

Port of tests/test_no_bare_print.py (verdict-identical; the engine's
pragma replaces that test's ALLOWED dict — it was empty at port time).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, Repo, Rule

#: cli/ prints to the terminal by design; that is its job.
_EXCLUDED_PREFIX = "ncnet_tpu/cli/"


class BarePrintRule(Rule):
    rule_id = "bare-print"
    description = ("bare print() in library code (use ncnet_tpu.obs.event "
                   "or file=sys.stderr); cli/ exempt")

    def check(self, repo: Repo) -> Iterable[Finding]:
        for sf in repo.selected():
            if sf.rel.startswith(_EXCLUDED_PREFIX):
                continue
            try:
                tree = sf.tree
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "print"
                        and not any(kw.arg == "file"
                                    for kw in node.keywords)):
                    yield Finding(
                        self.rule_id, sf.rel, node.lineno,
                        "bare print() in library code (use "
                        "ncnet_tpu.obs.event or file=sys.stderr)")
