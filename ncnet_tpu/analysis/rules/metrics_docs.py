"""``metrics-docs``: metric names Prometheus-safe and documented.

Port of tests/test_metrics_docs_lint.py (verdict-identical). Two
invariants:

1. **Prometheus safety** — every metric name passed to
   ``counter()``/``gauge()``/``histogram()`` anywhere under
   ``ncnet_tpu/`` is dotted lowercase (``[a-z0-9_.]``, no spaces, no
   leading digit/dot, no empty segments), so the ``/metrics``
   sanitization (dots -> underscores) can never produce an invalid or
   colliding Prometheus family name.

2. **Docs cross-check** — the serving / SLO / heartbeat / breaker /
   build-info families must match the canonical table in
   docs/OBSERVABILITY.md ("Serving & SLO metric families") BOTH ways:
   a family in code but not the table is undocumented; a family in the
   table but not the code is stale docs. Runtime-formatted segments
   (f-string fields) normalize to ``<field>`` on both sides.

Dynamic pass-through call sites (a bare variable forwarded by a
wrapper, e.g. ``obs.counter(name)``) are unresolvable and skipped;
every resolvable shape — literals, f-strings, conditional literals,
string concatenation — is linted. This is a ``full_repo`` rule: a
``--changed-only`` run must not let a partial file set fake a
stale-docs verdict.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from ..engine import Finding, Repo, Rule

DOC_PATH = "docs/OBSERVABILITY.md"
DOCS_SECTION = "## Serving & SLO metric families"

#: Families the docs table must cover, both ways (the fleet surface).
SCOPED_PREFIXES = ("serving.", "slo.", "obs.heartbeat.", "breaker.",
                   "ncnet.", "bulk.", "engine.", "device.", "trace.",
                   "train.")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_<>]+)*$")


def _field_name(expr: ast.AST) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return "x"


def _resolve(node: ast.AST) -> Optional[str]:
    """A metric-name expression -> normalized template, or None when
    the shape is a pure pass-through (bare variable) we cannot lint.

    f-string fields and other embedded dynamic parts become
    ``<field>`` (the attribute/variable name when there is one)."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                parts.append(f"<{_field_name(v.value)}>")
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve(node.left)
        right = _resolve(node.right)
        return ((left if left is not None else f"<{_field_name(node.left)}>")
                + (right if right is not None
                   else f"<{_field_name(node.right)}>"))
    return None


def _names(node: ast.AST) -> List[str]:
    """All normalized names one metric-name argument can evaluate to."""
    if isinstance(node, ast.IfExp):
        return _names(node.body) + _names(node.orelse)
    resolved = _resolve(node)
    # A lone pass-through variable is unresolvable — skip it; a partial
    # resolution (concat/f-string) keeps its <placeholders>.
    if resolved is None or resolved.startswith("<"):
        return []
    return [resolved]


def registered_metric_names(repo: Repo) -> List[Tuple[str, int, str]]:
    """(repo-relative path, lineno, normalized name) for every
    resolvable metric registration under ncnet_tpu/."""
    out = []
    for sf in repo.files():
        try:
            tree = sf.tree
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fname = (node.func.attr
                     if isinstance(node.func, ast.Attribute)
                     else node.func.id
                     if isinstance(node.func, ast.Name) else None)
            if fname not in ("counter", "gauge", "histogram"):
                continue
            for name in _names(node.args[0]):
                out.append((sf.rel, node.lineno, name))
    return out


def docs_table_families(repo: Repo) -> Optional[Set[str]]:
    """Backticked first-cell names from the canonical docs table, or
    None when the docs file / section is missing (reported as a
    finding by the rule)."""
    text = repo.read_doc(DOC_PATH)
    if text is None or DOCS_SECTION not in text:
        return None
    section = text.split(DOCS_SECTION, 1)[1].split("\n## ", 1)[0]
    return set(re.findall(r"^\|\s*`([^`]+)`\s*\|", section, re.MULTILINE))


class MetricsDocsRule(Rule):
    rule_id = "metrics-docs"
    description = ("metric names must be Prometheus-safe; fleet families "
                   "must match the docs/OBSERVABILITY.md table both ways")
    full_repo = True

    def check(self, repo: Repo) -> Iterable[Finding]:
        registered = registered_metric_names(repo)
        for rel, line, name in registered:
            # Placeholders stand in for one sanitized segment.
            probe = re.sub(r"<[^>]*>", "x", name)
            if not _NAME_RE.match(probe.replace("<", "").replace(">", "")):
                yield Finding(
                    self.rule_id, rel, line,
                    f"metric name {name!r} is not dotted lowercase "
                    f"[a-z0-9_.] (docs/OBSERVABILITY.md metric naming)",
                    symbol=name)
            elif ".." in probe or probe.endswith("."):
                yield Finding(
                    self.rule_id, rel, line,
                    f"metric name {name!r} has an empty segment",
                    symbol=name)
        docs = docs_table_families(repo)
        if docs is None:
            yield Finding(
                self.rule_id, DOC_PATH, 1,
                f"{DOC_PATH} lost its {DOCS_SECTION!r} section",
                symbol="docs-section")
            return
        if not docs:
            yield Finding(self.rule_id, DOC_PATH, 1,
                          "the family table has no rows",
                          symbol="docs-section")
            return
        code_sites = {}
        for rel, line, name in registered:
            if name.startswith(SCOPED_PREFIXES):
                code_sites.setdefault(name, (rel, line))
        for name in sorted(set(code_sites) - docs):
            rel, line = code_sites[name]
            yield Finding(
                self.rule_id, rel, line,
                f"metric family {name!r} missing from the "
                f"{DOC_PATH} 'Serving & SLO metric families' table",
                symbol=name)
        for name in sorted(docs - set(code_sites)):
            yield Finding(
                self.rule_id, DOC_PATH, 1,
                f"{DOC_PATH} lists family {name!r} no code registers "
                f"(stale row)",
                symbol=name)
