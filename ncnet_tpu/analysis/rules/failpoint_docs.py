"""``failpoint-docs``: every failpoint site documented, both ways.

Port of tests/test_failpoint_docs_lint.py (verdict-identical). An AST
walk over ``ncnet_tpu/`` collects every *named* failpoint plant —
``failpoints.fire("site", ...)`` and ``failpoints.corrupt("site",
...)`` with a literal first argument — and cross-checks the set
against the "Planted sites" table in docs/RELIABILITY.md:

* a site in code but not the table is an undocumented chaos hook
  (nobody will ever arm it, so its failure path stays untested);
* a site in the table but not the code is stale docs (a chaos spec
  naming it silently arms nothing — worse than an error).

One docs row may carry several backticked site names in its first cell
(the checkpoint family does); all of them count. ``full_repo``: a
partial ``--changed-only`` set must not fake stale-docs verdicts.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from ..engine import Finding, Repo, Rule

DOC_PATH = "docs/RELIABILITY.md"
DOCS_MARKER = "Planted sites"

_SITE_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")


def planted_sites(repo: Repo) -> List[Tuple[str, int, str]]:
    """(repo-relative path, lineno, site) for every literal-named plant
    under ncnet_tpu/. Non-literal first args are skipped — sites must
    be grep-able string literals by convention."""
    out = []
    for sf in repo.files():
        try:
            tree = sf.tree
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in ("fire", "corrupt")
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "failpoints"):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((sf.rel, node.lineno, arg.value))
    return out


def docs_table_sites(repo: Repo) -> Optional[Set[str]]:
    """All backticked names from the site table's first column, or None
    when the docs file / marker is missing."""
    text = repo.read_doc(DOC_PATH)
    if text is None or DOCS_MARKER not in text:
        return None
    section = text.split(DOCS_MARKER, 1)[1].split("\n## ", 1)[0]
    sites: Set[str] = set()
    for cell in re.findall(r"^\|([^|]*)\|", section, re.MULTILINE):
        sites.update(re.findall(r"`([a-z][a-z0-9_.]*)`", cell))
    sites.discard("failpoints.fire")  # the grep hint in the intro text
    return sites


class FailpointDocsRule(Rule):
    rule_id = "failpoint-docs"
    description = ("failpoint sites must be dotted lowercase and match "
                   "the docs/RELIABILITY.md 'Planted sites' table both "
                   "ways")
    full_repo = True

    def check(self, repo: Repo) -> Iterable[Finding]:
        planted = planted_sites(repo)
        for rel, line, site in planted:
            if not _SITE_RE.match(site):
                yield Finding(
                    self.rule_id, rel, line,
                    f"failpoint site {site!r} must be dotted lowercase "
                    f"(domain.site)",
                    symbol=site)
        docs = docs_table_sites(repo)
        if docs is None:
            yield Finding(
                self.rule_id, DOC_PATH, 1,
                f"{DOC_PATH} lost its {DOCS_MARKER!r} table intro",
                symbol="docs-section")
            return
        if not docs:
            yield Finding(self.rule_id, DOC_PATH, 1,
                          "the Planted sites table has no rows",
                          symbol="docs-section")
            return
        code_sites = {}
        for rel, line, site in planted:
            code_sites.setdefault(site, (rel, line))
        for site in sorted(set(code_sites) - docs):
            rel, line = code_sites[site]
            yield Finding(
                self.rule_id, rel, line,
                f"failpoint site {site!r} missing from the {DOC_PATH} "
                f"'Planted sites' table",
                symbol=site)
        for site in sorted(docs - set(code_sites)):
            yield Finding(
                self.rule_id, DOC_PATH, 1,
                f"{DOC_PATH} lists failpoint site {site!r} no code "
                f"plants (stale row)",
                symbol=site)
