"""``recompile-hazard``: unstable values flowing into jit/cache keys.

The serving engine buckets compiled programs by key (resolution bucket,
feature kind, match mode); the feature cache keys persisted artifacts.
Three mistakes silently wreck those keys:

* **Unhashable values** — a ``list``/``dict``/``set`` (or
  ``np.array``) in a jit bucket key raises ``TypeError`` at lookup
  time, or worse, gets stringified differently per process.
* **Nondeterministic values** — ``time.*``/``random.*``/``uuid.*``/
  ``id()`` in a key means every process (or every call) computes a
  fresh key: a 100% cache-miss rate that profiles as "recompiles
  forever" (exactly the stall PR 4's compile telemetry counts).
  ``os.stat`` mtimes are deliberately allowed — the model cache key
  uses them to *invalidate on change*, which is the point.
* **Dict iteration order** — ``d.items()`` feeding a key is stable
  within one process but not across processes/runs; keys built from
  mappings must go through ``sorted(...)`` (the metrics registry's
  ``label_key`` is the reference idiom).

*Key expressions* are recognized syntactically: assignments to names
ending in ``key``, keyword arguments ``*_key=`` (and bare ``key=``
outside the ``sorted``/``min``/``max`` family), and return values of
functions named ``*_key``. Hash-sanitizers (``tuple``, ``frozenset``,
``str``, ``repr``, ``json.dumps``, ``hashlib.*``, ``.hexdigest()``,
``"".join``, and the repo's own ``format_series`` — it canonicalizes
labels into a sorted string key) excuse the unhashable check; only
``sorted(...)`` (or ``format_series``) excuses dict iteration. ``@jax.jit(static_argnums=...)`` parameters with
unhashable defaults are flagged too — static args must be hashable.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ..engine import Finding, Repo, Rule, dotted_name

_UNHASHABLE_NODES = (ast.List, ast.Dict, ast.Set,
                     ast.ListComp, ast.SetComp, ast.DictComp)

_UNHASHABLE_CALLS = {"list", "set", "dict", "bytearray",
                     "np.array", "np.asarray",
                     "numpy.array", "numpy.asarray"}

_NONDET_EXACT = {"id", "os.urandom", "uuid.uuid4", "uuid.uuid1"}
_NONDET_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "uuid.")

#: Wrapping any of these makes the value hashable/stable regardless of
#: what's inside (a digest of a list is a fine key).
_HASH_SANITIZERS = {"tuple", "frozenset", "str", "repr", "bytes",
                    "json.dumps", "format", "format_series"}
_HASH_SANITIZER_METHODS = {"hexdigest", "digest", "join", "format"}

#: ``key=`` on these is a sort-comparator, not a cache key.
_SORT_FAMILY = {"sorted", "min", "max", "sort", "nsmallest", "nlargest",
                "groupby"}


def _call_sanitizes(call: ast.Call) -> bool:
    fn = dotted_name(call.func)
    if fn in _HASH_SANITIZERS or (fn or "").startswith("hashlib."):
        return True
    if isinstance(call.func, ast.Attribute):
        return call.func.attr in _HASH_SANITIZER_METHODS
    return False


class _KeyScan:
    """Walk one key expression, tracking sanitizer context."""

    def __init__(self):
        self.hits: List[Tuple[int, str]] = []

    def scan(self, node: ast.AST, hash_safe: bool,
             order_safe: bool) -> None:
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn is not None:
                if fn in _NONDET_EXACT or fn.startswith(_NONDET_PREFIXES):
                    self.hits.append((
                        node.lineno,
                        f"nondeterministic {fn}() in a cache/bucket key "
                        f"defeats caching (fresh key every call)"))
                elif not hash_safe and fn in _UNHASHABLE_CALLS:
                    self.hits.append((
                        node.lineno,
                        f"unhashable {fn}() in a cache/bucket key "
                        f"(wrap in tuple()/frozenset() or hash it)"))
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("items", "keys", "values")
                    and not node.args and not order_safe):
                self.hits.append((
                    node.lineno,
                    f".{node.func.attr}() iteration order feeds this key; "
                    f"wrap in sorted(...) for a cross-run-stable key"))
            child_hash = hash_safe or _call_sanitizes(node)
            child_order = order_safe or fn in ("sorted", "format_series")
            for child in ast.iter_child_nodes(node):
                self.scan(child, child_hash, child_order)
            return
        if isinstance(node, _UNHASHABLE_NODES) and not hash_safe:
            kind = type(node).__name__.lower()
            self.hits.append((
                node.lineno,
                f"unhashable {kind} literal in a cache/bucket key "
                f"(use a tuple)"))
        for child in ast.iter_child_nodes(node):
            self.scan(child, hash_safe, order_safe)


def _static_indices(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """``static_argnums``/``static_argnames`` from a jit call's kwargs."""
    nums: Set[int] = set()
    names: Set[str] = set()

    def consts(node: ast.AST) -> list:
        if isinstance(node, ast.Constant):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [c.value for c in node.elts
                    if isinstance(c, ast.Constant)]
        return []

    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= {v for v in consts(kw.value) if isinstance(v, int)}
        elif kw.arg == "static_argnames":
            names |= {v for v in consts(kw.value) if isinstance(v, str)}
    return nums, names


def _check_static_defaults(func: ast.AST, nums: Set[int],
                           names: Set[str]) -> Iterable[Tuple[int, str]]:
    args = func.args.args
    defaults = func.args.defaults  # align to the LAST len(defaults) args
    offset = len(args) - len(defaults)
    for i, arg in enumerate(args):
        if i not in nums and arg.arg not in names:
            continue
        d = i - offset
        if 0 <= d < len(defaults) and isinstance(defaults[d],
                                                 _UNHASHABLE_NODES):
            yield (defaults[d].lineno,
                   f"static arg {arg.arg!r} of jitted {func.name}() has "
                   f"an unhashable default (static args must be "
                   f"hashable)")


class RecompileHazardRule(Rule):
    rule_id = "recompile-hazard"
    description = ("unhashable / nondeterministic values and unsorted "
                   "dict iteration flowing into jit bucket keys, cache "
                   "keys, and static_argnums")

    def check(self, repo: Repo) -> Iterable[Finding]:
        for sf in repo.selected():
            try:
                tree = sf.tree
            except SyntaxError:
                continue  # trace-purity already reports unparseable files
            yield from self._check_tree(sf.rel, tree)

    def _check_tree(self, rel: str, tree: ast.AST) -> Iterable[Finding]:
        for node in ast.walk(tree):
            exprs: List[Tuple[ast.AST, str]] = []
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Name)
                            and tgt.id.lower().endswith("key")):
                        exprs.append((node.value, tgt.id))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt = node.target
                if (isinstance(tgt, ast.Name)
                        and tgt.id.lower().endswith("key")):
                    exprs.append((node.value, tgt.id))
            elif isinstance(node, ast.Call):
                callee = (dotted_name(node.func) or "").split(".")[-1]
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    if kw.arg.endswith("_key") or (
                            kw.arg == "key"
                            and callee not in _SORT_FAMILY):
                        exprs.append((kw.value, kw.arg))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name.lower().endswith("key"):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Return) and sub.value:
                            exprs.append((sub.value, node.name))
                yield from self._check_jit_statics(rel, node)
            for expr, symbol in exprs:
                scan = _KeyScan()
                scan.scan(expr, hash_safe=False, order_safe=False)
                for line, msg in scan.hits:
                    yield Finding(self.rule_id, rel, line, msg,
                                  symbol=symbol)

    def _check_jit_statics(self, rel: str,
                           func: ast.AST) -> Iterable[Finding]:
        for dec in func.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            fn = dotted_name(dec.func)
            is_jit = fn in ("jax.jit", "jit")
            is_partial_jit = (fn in ("partial", "functools.partial")
                              and dec.args
                              and dotted_name(dec.args[0])
                              in ("jax.jit", "jit"))
            if not (is_jit or is_partial_jit):
                continue
            nums, names = _static_indices(dec)
            for line, msg in _check_static_defaults(func, nums, names):
                yield Finding(self.rule_id, rel, line, msg,
                              symbol=func.name)
