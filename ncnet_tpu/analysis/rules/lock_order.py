"""``lock-order``: deadlock hazards in the lock-acquisition graph.

The serving stack holds real locks across real calls: the batcher's
condition is held while metrics record, the engine's store lock is held
across a feature-cache put, the fleet kills replicas that own batchers.
Two threads acquiring the same two locks in opposite orders deadlock
only under load — the one failure mode no unit test schedule reliably
reproduces. So this rule derives the acquisition graph statically:

* **Lock identity** — ``Class._attr`` for instance locks
  (``self._x = threading.Lock()/RLock()/Condition()``, or
  ``self._x = <param>`` where the parameter is named ``lock``/``cond``
  — the metrics children receive their locks that way), ``module._name``
  for module-level locks, and ``Class._m`` for contextmanager methods
  with ``lock`` in the name (the feature cache's flock wrapper).
* **Acquisition sites** — ``with`` statements only: ``with self._x:``,
  ``with self.attr._x:`` (via the ``self.attr = ClassName(...)`` type
  map), ``with modlock:``, ``with self._m():``. ``Condition.wait`` is
  not an acquisition edge (it *releases* while waiting).
* **Edges** — lock A is held at a site that acquires B directly
  (nested ``with``) or calls code that *may acquire* B. ``may_acquire``
  is a fixed point over a resolved call graph: ``self.m()``,
  ``self.attr.m()``, module-local ``f()``, ``alias.f()`` with one
  re-export hop (``from .. import obs`` → ``obs/__init__`` →
  ``from .metrics import counter``), ``Class(...)`` → ``__init__``,
  module-var methods (``_DEFAULT.counter``), and the metrics chain
  idiom ``obs.counter(...).inc()`` / ``.observe()`` / ``.set()``.

Any cycle (including a self-edge on a non-reentrant ``Lock``) is a
deadlock-hazard finding. The acquisition-order table is emitted into
docs/ANALYSIS.md between generated-block markers; this rule also
verifies that block is fresh (``tools/ncnet_lint.py --write-docs``
regenerates it).

Unresolved calls (cross-package helpers, stdlib) contribute no edges:
the graph is an under-approximation of runtime behavior, which is why
lock scope is kept to the concurrency-bearing trees below.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..engine import Finding, Repo, Rule, dotted_name

#: The concurrency-bearing trees the graph is built from (ISSUE 10).
SCOPE = (
    "ncnet_tpu/serving/",
    "ncnet_tpu/obs/",
    "ncnet_tpu/reliability/",
    "ncnet_tpu/pipeline/",
    "ncnet_tpu/evals/feature_cache.py",
    # Elastic membership plane (ISSUE 20): the lease-heartbeat thread
    # and the flock'd generation mutations.
    "ncnet_tpu/parallel/membership.py",
    "ncnet_tpu/training/elastic.py",
)

#: Generated-block markers in docs/ANALYSIS.md.
DOC_PATH = "docs/ANALYSIS.md"
BEGIN_MARK = "<!-- BEGIN GENERATED: lock-order -->"
END_MARK = "<!-- END GENERATED: lock-order -->"

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
}

#: Reentrant kinds: a same-thread re-acquire does not deadlock
#: (Condition wraps an RLock by default), so self-edges are exempt.
_REENTRANT = {"RLock", "Condition", "contextmanager"}

#: The metrics chain idiom: ``<...>.counter(...).inc()`` resolves to
#: the child-metric method without return-type inference.
_CHAIN_FACTORY = {"counter": "Counter", "gauge": "Gauge",
                  "histogram": "Histogram"}
_CHAIN_METHODS = {"inc", "set", "observe"}


def _is_contextmanager(func: ast.AST) -> bool:
    for dec in getattr(func, "decorator_list", ()):
        if dotted_name(dec) in ("contextmanager",
                                "contextlib.contextmanager"):
            return True
    return False


class _Class:
    def __init__(self, name: str, rel: str):
        self.name = name
        self.rel = rel
        self.methods: Dict[str, ast.AST] = {}
        self.attr_locks: Dict[str, Tuple[str, int]] = {}  # attr -> kind,line
        self.attr_types: Dict[str, str] = {}  # attr -> class-name string
        self.pseudo_locks: Dict[str, int] = {}  # method name -> def line


class _Module:
    def __init__(self, rel: str, tree: ast.AST):
        self.rel = rel
        # ncnet_tpu/obs/metrics.py -> pkg ["ncnet_tpu","obs"], base
        # "metrics". A package __init__ IS its package: relative
        # imports inside it resolve against the package itself.
        parts = rel[: -len(".py")].split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
            self.pkg = parts
        else:
            self.pkg = parts[:-1]
        self.base = parts[-1]
        self.funcs: Dict[str, ast.AST] = {}
        self.classes: Dict[str, _Class] = {}
        self.imports: Dict[str, str] = {}  # alias -> module rel path
        self.from_binds: Dict[str, Tuple[str, str]] = {}  # name->(rel,name)
        self.mod_locks: Dict[str, Tuple[str, int]] = {}  # name->(kind,line)
        self.mod_vars: Dict[str, str] = {}  # name -> class-name string
        self._index(tree)

    def _module_rel(self, dotted: Sequence[str]) -> Optional[str]:
        """Dotted module parts -> repo-relative path, if it exists as a
        module or package in the file set (checked by the caller)."""
        return "/".join(dotted)

    def _resolve_import(self, level: int, module: str) -> List[str]:
        if level == 0:
            return module.split(".") if module else []
        base = self.pkg[: len(self.pkg) - (level - 1)]
        if module:
            base = base + module.split(".")
        return base

    def _index(self, tree: ast.AST) -> None:
        for node in tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    self.imports[name] = alias.name.replace(".", "/")
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_import(node.level, node.module or "")
                for alias in node.names:
                    name = alias.asname or alias.name
                    # `from X import y`: y may itself be module X/y, or
                    # an object in module X — record both candidates;
                    # the resolver checks against the real file set.
                    self.imports.setdefault(
                        name, "/".join(target + [alias.name]))
                    self.from_binds[name] = ("/".join(target), alias.name)
            elif isinstance(node, ast.FunctionDef):
                self.funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = self._index_class(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name) and isinstance(node.value,
                                                            ast.Call):
                    ctor = dotted_name(node.value.func)
                    kind = _LOCK_CTORS.get(ctor or "")
                    if kind:
                        self.mod_locks[tgt.id] = (kind, node.lineno)
                    elif ctor:
                        self.mod_vars[tgt.id] = ctor.split(".")[-1]

    def _index_class(self, node: ast.ClassDef) -> _Class:
        cls = _Class(node.name, self.rel)
        for item in node.body:
            if (isinstance(item, ast.AnnAssign)
                    and isinstance(item.target, ast.Name)):
                # Dataclass-style field lock: ``lock: threading.Lock =
                # field(default_factory=threading.Lock)``.
                kind = _LOCK_CTORS.get(dotted_name(item.annotation) or "")
                if kind:
                    cls.attr_locks[item.target.id] = (kind, item.lineno)
                continue
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            cls.methods[item.name] = item
            if _is_contextmanager(item) and "lock" in item.name:
                cls.pseudo_locks[item.name] = item.lineno
            params = {a.arg for a in item.args.args}
            for sub in ast.walk(item):
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1):
                    continue
                tgt = sub.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                if isinstance(sub.value, ast.Call):
                    ctor = dotted_name(sub.value.func)
                    kind = _LOCK_CTORS.get(ctor or "")
                    if kind:
                        cls.attr_locks[tgt.attr] = (kind, sub.lineno)
                    elif ctor:
                        cls.attr_types.setdefault(
                            tgt.attr, ctor.split(".")[-1])
                elif (isinstance(sub.value, ast.Name)
                      and sub.value.id in params
                      and (sub.value.id in ("lock", "cond")
                           or sub.value.id.endswith(("_lock", "_cond")))):
                    # Lock handed in via a constructor parameter (the
                    # metrics children): non-reentrant by assumption.
                    cls.attr_locks.setdefault(
                        tgt.attr, ("Lock", sub.lineno))
        return cls


class _Graph:
    """Lock nodes + ordered acquisition edges with one example site."""

    def __init__(self):
        self.nodes: Dict[str, Tuple[str, str, int]] = {}  # kind, rel, line
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_node(self, name: str, kind: str, rel: str, line: int) -> None:
        self.nodes.setdefault(name, (kind, rel, line))

    def add_edge(self, a: str, b: str, rel: str, line: int,
                 via: str) -> None:
        self.edges.setdefault((a, b), (rel, line, via))

    def cycles(self) -> List[List[str]]:
        """Tarjan SCCs of size > 1, plus Lock self-loops as [n, n]."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in adj.get(v, ()):  # iterative depth is tiny here
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    out.append(sorted(scc))

        for v in sorted(self.nodes):
            if v not in index:
                strongconnect(v)
        for (a, b) in sorted(self.edges):
            if a == b and self.nodes[a][0] not in _REENTRANT:
                out.append([a, a])
        return out

    def topo_order(self) -> List[str]:
        """Kahn topological order (alphabetical tie-break); falls back
        to alphabetical when a cycle blocks it."""
        indeg = {n: 0 for n in self.nodes}
        for a, b in self.edges:
            if a != b:
                indeg[b] += 1
        ready = sorted(n for n, d in indeg.items() if d == 0)
        out: List[str] = []
        while ready:
            n = ready.pop(0)
            out.append(n)
            for (a, b), _ in sorted(self.edges.items()):
                if a == n and b != n:
                    indeg[b] -= 1
                    if indeg[b] == 0 and b not in out:
                        ready.append(b)
            ready.sort()
        if len(out) != len(self.nodes):
            return sorted(self.nodes)
        return out


class _Analyzer:
    def __init__(self, repo: Repo, scope: Tuple[str, ...] = SCOPE):
        self.repo = repo
        self.scope = scope
        self.modules: Dict[str, _Module] = {}
        self.class_index: Dict[str, _Class] = {}
        self.graph = _Graph()
        self.findings: List[Finding] = []
        # function key -> (module, class-or-None, ast node)
        self.funcs: Dict[str, Tuple[_Module, Optional[_Class], ast.AST]] = {}
        self.may: Dict[str, Set[str]] = {}
        self.calls: Dict[str, Set[str]] = {}
        self.direct: Dict[str, Set[Tuple[str, int]]] = {}

    # -- pass 1: index ----------------------------------------------------

    def build(self) -> None:
        for sf in self.repo.files(under=self.scope):
            try:
                mod = _Module(sf.rel, sf.tree)
            except SyntaxError as exc:
                self.findings.append(Finding(
                    "lock-order", sf.rel, exc.lineno or 1,
                    f"unparseable file: {exc.msg}"))
                continue
            self.modules[mod.rel] = mod
            for cls in mod.classes.values():
                self.class_index[cls.name] = cls
        for mod in self.modules.values():
            for name, (kind, line) in mod.mod_locks.items():
                self.graph.add_node(f"{mod.base}.{name}", kind,
                                    mod.rel, line)
            for cls in mod.classes.values():
                for attr, (kind, line) in cls.attr_locks.items():
                    self.graph.add_node(f"{cls.name}.{attr}", kind,
                                        mod.rel, line)
                for meth, line in cls.pseudo_locks.items():
                    self.graph.add_node(f"{cls.name}.{meth}",
                                        "contextmanager", mod.rel, line)
                for meth, node in cls.methods.items():
                    self._register(f"{mod.rel}::{cls.name}.{meth}",
                                   mod, cls, node)
            for name, node in mod.funcs.items():
                self._register(f"{mod.rel}::{name}", mod, None, node)
        self._collect_all()
        self._propagate()
        self._edges_all()

    def _register(self, key: str, mod: _Module, cls: Optional[_Class],
                  node: ast.AST) -> None:
        self.funcs[key] = (mod, cls, node)
        self.calls[key] = set()
        self.direct[key] = set()

    # -- resolution helpers ----------------------------------------------

    def _module_by_path(self, parts_path: str) -> Optional[_Module]:
        for cand in (parts_path + ".py", parts_path + "/__init__.py"):
            if cand in self.modules:
                return self.modules[cand]
        return None

    def _attr_class(self, cls: Optional[_Class],
                    attr: str) -> Optional[_Class]:
        if cls is None:
            return None
        tname = cls.attr_types.get(attr)
        return self.class_index.get(tname) if tname else None

    def _lock_of(self, expr: ast.AST, mod: _Module,
                 cls: Optional[_Class]) -> Optional[str]:
        """The lock node a ``with`` context expression acquires."""
        if isinstance(expr, ast.Call):
            fn = dotted_name(expr.func)
            if fn and fn.startswith("self.") and cls is not None:
                meth = fn.split(".")[-1]
                if fn.count(".") == 1 and meth in cls.pseudo_locks:
                    return f"{cls.name}.{meth}"
            return None
        name = dotted_name(expr)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2 and parts[1] in cls.attr_locks:
                return f"{cls.name}.{parts[1]}"
            if len(parts) == 3:
                owner = self._attr_class(cls, parts[1])
                if owner is not None and parts[2] in owner.attr_locks:
                    return f"{owner.name}.{parts[2]}"
            return None
        if len(parts) == 1 and parts[0] in mod.mod_locks:
            return f"{mod.base}.{parts[0]}"
        if len(parts) == 2:
            # alias._lock for a module-level lock in an imported module
            target = mod.imports.get(parts[0])
            if target:
                tmod = self._module_by_path(target)
                if tmod is not None and parts[1] in tmod.mod_locks:
                    return f"{tmod.base}.{parts[1]}"
        return None

    def _func_in_module(self, tmod: _Module, name: str,
                        hop: bool = True) -> List[str]:
        if name in tmod.funcs:
            return [f"{tmod.rel}::{name}"]
        if name in tmod.classes and "__init__" in tmod.classes[name].methods:
            return [f"{tmod.rel}::{name}.__init__"]
        if hop and name in tmod.from_binds:
            # one re-export hop: obs/__init__ `from .metrics import counter`
            src, orig = tmod.from_binds[name]
            smod = self._module_by_path(src)
            if smod is not None:
                return self._func_in_module(smod, orig, hop=False)
        return []

    def _resolve_call(self, call: ast.Call, mod: _Module,
                      cls: Optional[_Class]) -> List[str]:
        out: List[str] = []
        fn = call.func
        # metrics chain: <anything>.counter(...).inc()
        if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Call)
                and fn.attr in _CHAIN_METHODS):
            inner = dotted_name(fn.value.func)
            factory = (inner or "").split(".")[-1]
            child_cls = _CHAIN_FACTORY.get(factory)
            if child_cls and child_cls in self.class_index:
                owner = self.class_index[child_cls]
                if fn.attr in owner.methods:
                    out.append(f"{owner.rel}::{child_cls}.{fn.attr}")
            # the inner factory call is visited separately by the walk
            return out
        name = dotted_name(fn)
        if not name:
            return out
        parts = name.split(".")
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2:
                if parts[1] in cls.methods:
                    out.append(f"{cls.rel}::{cls.name}.{parts[1]}")
            elif len(parts) == 3:
                owner = self._attr_class(cls, parts[1])
                if owner is not None and parts[2] in owner.methods:
                    out.append(f"{owner.rel}::{owner.name}.{parts[2]}")
            return out
        if len(parts) == 1:
            if parts[0] in mod.funcs:
                out.append(f"{mod.rel}::{parts[0]}")
            elif parts[0] in mod.from_binds:
                src, orig = mod.from_binds[parts[0]]
                smod = self._module_by_path(src)
                if smod is not None:
                    out.extend(self._func_in_module(smod, orig, hop=False))
                elif parts[0] in self.class_index:
                    c = self.class_index[parts[0]]
                    if "__init__" in c.methods:
                        out.append(f"{c.rel}::{c.name}.__init__")
            elif parts[0] in self.class_index:
                c = self.class_index[parts[0]]
                if "__init__" in c.methods:
                    out.append(f"{c.rel}::{c.name}.__init__")
            return out
        if len(parts) == 2:
            head, meth = parts
            target = mod.imports.get(head)
            if target:
                tmod = self._module_by_path(target)
                if tmod is not None:
                    out.extend(self._func_in_module(tmod, meth))
                    return out
            if head in mod.mod_vars:
                owner = self.class_index.get(mod.mod_vars[head])
                if owner is not None and meth in owner.methods:
                    out.append(f"{owner.rel}::{owner.name}.{meth}")
                return out
            if head in self.class_index:  # ClassName.static_method(...)
                owner = self.class_index[head]
                if meth in owner.methods:
                    out.append(f"{owner.rel}::{owner.name}.{meth}")
        return out

    # -- pass 2a: direct acquisitions + call graph ------------------------

    def _collect_all(self) -> None:
        for key, (mod, cls, node) in self.funcs.items():
            self._collect(node, key, mod, cls)

    def _collect(self, node: ast.AST, key: str, mod: _Module,
                 cls: Optional[_Class]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    lk = self._lock_of(item.context_expr, mod, cls)
                    if lk:
                        self.direct[key].add((lk, item.context_expr.lineno))
            elif isinstance(sub, ast.Call):
                for tgt in self._resolve_call(sub, mod, cls):
                    if tgt != key:
                        self.calls[key].add(tgt)

    def _propagate(self) -> None:
        for key in self.funcs:
            self.may[key] = {lk for lk, _ in self.direct[key]}
        changed = True
        while changed:
            changed = False
            for key, callees in self.calls.items():
                for callee in callees:
                    extra = self.may.get(callee, set()) - self.may[key]
                    if extra:
                        self.may[key] |= extra
                        changed = True

    # -- pass 2b: held-context edges --------------------------------------

    def _edges_all(self) -> None:
        for key, (mod, cls, node) in self.funcs.items():
            for stmt in getattr(node, "body", ()):
                self._edge_walk(stmt, (), mod, cls)

    def _edge_walk(self, node: ast.AST, held: Tuple[str, ...],
                   mod: _Module, cls: Optional[_Class]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                lk = self._lock_of(item.context_expr, mod, cls)
                if lk:
                    for h in held + tuple(acquired):
                        self.graph.add_edge(h, lk, mod.rel,
                                            item.context_expr.lineno,
                                            "nested with")
                    acquired.append(lk)
                else:
                    self._edge_walk(item.context_expr, held, mod, cls)
            for stmt in node.body:
                self._edge_walk(stmt, held + tuple(acquired), mod, cls)
            return
        if isinstance(node, ast.Call):
            if held:
                for tgt in self._resolve_call(node, mod, cls):
                    for lk in sorted(self.may.get(tgt, ())):
                        short = tgt.split("::")[-1]
                        for h in held:
                            self.graph.add_edge(h, lk, mod.rel,
                                                node.lineno,
                                                f"calls {short}")
        # Nested defs/lambdas walk with the current held set: the
        # serving flush callbacks run synchronously under the lock, and
        # an escaping closure over-approximates to extra edges, never
        # missed ones.
        for child in ast.iter_child_nodes(node):
            self._edge_walk(child, held, mod, cls)


def build_graph(repo: Repo) -> _Graph:
    """The lock-acquisition graph for the scoped trees (public: the
    docs writer in tools/ncnet_lint.py renders it)."""
    an = _Analyzer(repo)
    an.build()
    return an.graph


def render_lock_table(graph: _Graph) -> str:
    """The markdown acquisition-order table (generated-block body)."""
    lines = [
        "Generated by `python tools/ncnet_lint.py --write-docs` — do not",
        "edit by hand. Locks are listed in acquisition order: a lock may",
        "only be taken while holding locks that appear ABOVE it.",
        "",
        "| Order | Lock | Kind | Defined at | May acquire while held |",
        "|---|---|---|---|---|",
    ]
    order = graph.topo_order()
    succ: Dict[str, List[str]] = {}
    for (a, b), _site in sorted(graph.edges.items()):
        if a != b:
            succ.setdefault(a, []).append(b)
    for i, name in enumerate(order, start=1):
        kind, rel, line = graph.nodes[name]
        outs = ", ".join(f"`{s}`" for s in sorted(set(succ.get(name, ()))))
        lines.append(
            f"| {i} | `{name}` | {kind} | `{rel}:{line}` "
            f"| {outs or '(leaf)'} |"
        )
    cycles = graph.cycles()
    lines.append("")
    if cycles:
        lines.append("**Deadlock hazards (cycles):** "
                     + "; ".join(" -> ".join(c + [c[0]]) for c in cycles))
    else:
        lines.append("The graph is **acyclic**: no lock-order deadlock is "
                     "possible among these locks.")
    return "\n".join(lines)


def _normalize(text: str) -> str:
    return "\n".join(l.rstrip() for l in text.strip().splitlines())


class LockOrderRule(Rule):
    rule_id = "lock-order"
    description = ("deadlock-hazard cycles in the lock-acquisition graph "
                   "across serving/, obs/, reliability/, pipeline/, and "
                   "the feature cache; docs/ANALYSIS.md table freshness")
    full_repo = True  # the graph must never be built from a partial set

    def check(self, repo: Repo) -> Iterable[Finding]:
        an = _Analyzer(repo)
        an.build()
        yield from an.findings
        graph = an.graph
        for cyc in graph.cycles():
            first = cyc[0]
            kind, rel, line = graph.nodes[first]
            if len(set(cyc)) == 1:
                msg = (f"non-reentrant {kind} {first!r} may be "
                       f"re-acquired while already held (self-deadlock)")
            else:
                path = " -> ".join(cyc + [cyc[0]])
                msg = (f"lock-order cycle (deadlock hazard): {path}; "
                       f"break it by fixing one acquisition order")
            yield Finding(self.rule_id, rel, line, msg,
                          symbol="->".join(cyc))
        yield from self._check_docs(repo, graph)

    def _check_docs(self, repo: Repo, graph: _Graph) -> Iterable[Finding]:
        doc = repo.read_doc(DOC_PATH)
        want = _normalize(render_lock_table(graph))
        if doc is None:
            yield Finding(self.rule_id, DOC_PATH, 1,
                          f"{DOC_PATH} is missing; run "
                          "`python tools/ncnet_lint.py --write-docs`",
                          symbol="docs-block")
            return
        if BEGIN_MARK not in doc or END_MARK not in doc:
            yield Finding(self.rule_id, DOC_PATH, 1,
                          f"{DOC_PATH} lacks the generated lock-order "
                          f"block markers ({BEGIN_MARK}); run "
                          "`python tools/ncnet_lint.py --write-docs`",
                          symbol="docs-block")
            return
        begin_line = doc[: doc.index(BEGIN_MARK)].count("\n") + 1
        body = doc.split(BEGIN_MARK, 1)[1].split(END_MARK, 1)[0]
        if _normalize(body) != want:
            yield Finding(self.rule_id, DOC_PATH, begin_line,
                          "generated lock-order table is stale; run "
                          "`python tools/ncnet_lint.py --write-docs`",
                          symbol="docs-block")


def write_docs_block(repo: Repo) -> bool:
    """Rewrite the generated block in docs/ANALYSIS.md in place.

    Returns True when the file changed. The surrounding prose is left
    untouched; only the text between the markers is regenerated.
    """
    import os

    doc_path = os.path.join(repo.root, DOC_PATH)
    try:
        with open(doc_path, encoding="utf-8") as fh:
            doc = fh.read()
    except OSError:
        return False
    if BEGIN_MARK not in doc or END_MARK not in doc:
        return False
    head, rest = doc.split(BEGIN_MARK, 1)
    _stale, tail = rest.split(END_MARK, 1)
    table = render_lock_table(build_graph(repo))
    new = head + BEGIN_MARK + "\n" + table + "\n" + END_MARK + tail
    if new == doc:
        return False
    with open(doc_path, "w", encoding="utf-8") as fh:
        fh.write(new)
    return True
