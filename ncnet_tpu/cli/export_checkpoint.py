"""Export a native checkpoint to a reference-loadable `.pth.tar`.

The inverse of tools/convert_checkpoint.py: lets weights trained in this
framework travel BACK to the reference implementation (whose restore path,
lib/model.py:211-248, reads the arch params from the stored argparse
Namespace and the pre-permuted Conv4d weights from the state dict).

Usage:
    ncnet-export-checkpoint <native_ckpt_dir> <out.pth.tar>
"""

import argparse
import sys


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("src", help="native checkpoint directory (training.checkpoint)")
    p.add_argument("dst", help="output .pth.tar path")
    p.add_argument(
        "--verify", action="store_true", default=True,
        help="re-import the exported file and compare pytrees (default on)",
    )
    p.add_argument("--no-verify", dest="verify", action="store_false")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from ncnet_tpu.models.convert import (
        export_reference_checkpoint,
        load_reference_checkpoint,
    )
    from ncnet_tpu.training.checkpoint import load_checkpoint

    restored = load_checkpoint(args.src)
    config, params = restored["config"], restored["params"]
    export_reference_checkpoint(
        args.dst,
        params,
        config.backbone,
        config.ncons_kernel_sizes,
        config.ncons_channels,
        epoch=restored["meta"].get("epoch", 0),
    )
    n = sum(x.size for x in jax.tree.leaves(params))
    print(f"wrote {args.dst}: {config.backbone.cnn}, "
          f"ncons {tuple(config.ncons_kernel_sizes)}/"
          f"{tuple(config.ncons_channels)}, {n / 1e6:.1f}M params")

    if args.verify:
        re_params, arch = load_reference_checkpoint(args.dst)
        assert tuple(arch["ncons_kernel_sizes"]) == tuple(config.ncons_kernel_sizes)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            params,
            re_params,
        )
        print("round-trip verify OK (bit-exact)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
