"""Command-line entry points (train / eval_pf_pascal / eval_pf_willow / eval_tss / eval_inloc)."""
