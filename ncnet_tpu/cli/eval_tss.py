"""TSS dense-flow evaluation CLI (parity: eval_tss.py).

Writes per-pair `.flo` files for the external TSS evaluation kit under
`<flow_output_dir>/nc/<pair>/<flowN>.flo` (lib/eval_util.py:94-97).
"""

from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp

from ..data import TSSDataset, DataLoader
from ..evals import write_flow_output
from ..models.ncnet import ncnet_forward
from ..ops import corr_to_matches
from .common import build_model


def main(argv=None):
    parser = argparse.ArgumentParser(description="NCNet-TPU TSS flow eval")
    parser.add_argument("--checkpoint", type=str, default="")
    parser.add_argument("--image_size", type=int, default=400)
    parser.add_argument("--eval_dataset_path", type=str, default="datasets/tss/")
    parser.add_argument("--csv_file", type=str, default="test_pairs.csv")
    parser.add_argument("--flow_output_dir", type=str, default="datasets/tss/results/")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_workers", type=int, default=8)
    args = parser.parse_args(argv)

    config, params = build_model(checkpoint=args.checkpoint)
    dataset = TSSDataset(
        os.path.join(args.eval_dataset_path, args.csv_file),
        args.eval_dataset_path,
        output_size=(args.image_size, args.image_size),
    )
    loader = DataLoader(
        dataset, args.batch_size, shuffle=False,
        num_workers=args.num_workers,
    )

    @jax.jit
    def step(params, source, target):
        corr, _ = ncnet_forward(config, params, source, target)
        return corr_to_matches(corr, do_softmax=True)

    done = 0
    for batch in loader:
        xa, ya, xb, yb, _ = step(
            params,
            jnp.asarray(batch["source_image"]),
            jnp.asarray(batch["target_image"]),
        )
        bsz = batch["source_image"].shape[0]
        for b in range(bsz):
            matches_b = (xa[b : b + 1], ya[b : b + 1], xb[b : b + 1], yb[b : b + 1])
            write_flow_output(
                matches_b,
                batch["source_im_size"][b],
                batch["target_im_size"][b],
                batch["flow_path"][b],
                args.flow_output_dir,
            )
            done += 1
        print(f"[{done}/{len(dataset)}]", flush=True)
    print("Done!")


if __name__ == "__main__":
    main()
