"""PF-Pascal keypoint-transfer evaluation CLI (parity: eval_pf_pascal.py)."""

from __future__ import annotations

import argparse
import os

from ..data import PFPascalDataset
from .common import build_model
from .eval_pck import evaluate_pck


def main(argv=None):
    parser = argparse.ArgumentParser(description="NCNet-TPU PF-Pascal PCK eval")
    parser.add_argument("--checkpoint", type=str, default="")
    parser.add_argument("--image_size", type=int, default=400)
    parser.add_argument(
        "--eval_dataset_path", type=str, default="datasets/pf-pascal/"
    )
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_workers", type=int, default=8)
    parser.add_argument("--alpha", type=float, default=0.1,
                        help="PCK threshold (paper reports @0.1; the reference "
                        "code's default was 0.15)")
    parser.add_argument("--pck_procedure", type=str, default="scnet")
    args = parser.parse_args(argv)

    config, params = build_model(checkpoint=args.checkpoint)
    dataset = PFPascalDataset(
        os.path.join(args.eval_dataset_path, "image_pairs/test_pairs.csv"),
        args.eval_dataset_path,
        output_size=(args.image_size, args.image_size),
        pck_procedure=args.pck_procedure,
    )
    evaluate_pck(config, params, dataset, args.batch_size, args.alpha,
                 num_workers=args.num_workers)


if __name__ == "__main__":
    main()
