"""Shared CLI helpers: model construction from checkpoints, device batches."""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp

from ..models import BackboneConfig, NCNetConfig, ncnet_init
from ..models.convert import load_reference_checkpoint
from ..training.checkpoint import load_checkpoint


def _with_backbone_dtype(config: NCNetConfig, backbone_bf16: bool) -> NCNetConfig:
    """Opt the backbone into bf16 conv compute (TPU fast path)."""
    if not backbone_bf16:
        return config
    return dataclass_replace(
        config,
        backbone=dataclass_replace(config.backbone, compute_dtype="bfloat16"),
    )


def build_model(
    checkpoint: str = "",
    ncons_kernel_sizes=(5, 5, 5),
    ncons_channels=(16, 16, 1),
    backbone_cnn: str = "resnet101",
    relocalization_k_size: int = 0,
    half_precision: bool = False,
    backbone_bf16: bool = False,
    seed: int = 1,
) -> Tuple[NCNetConfig, dict]:
    """Build (config, params), restoring from a checkpoint when given.

    Checkpoint formats: a directory written by training.checkpoint (native),
    or a reference `.pth.tar` (converted on the fly). In both cases the
    stored architecture hyper-parameters override the CLI args, matching the
    reference restore rule (lib/model.py:217-220).
    """
    if checkpoint and not os.path.exists(checkpoint):
        raise SystemExit(
            f"checkpoint not found: {checkpoint!r} (expected a directory "
            "written by ncnet_tpu.training.checkpoint or a reference "
            ".pth.tar file)"
        )
    def check_consensus_arch(config, source: str):
        # Validate the RESOLVED architecture (a checkpoint's stored
        # hyper-parameters override the CLI args, so raw-arg validation
        # would both reject ignored args and miss a bad checkpoint). The
        # consensus stack must map back to a single-channel corr tensor
        # (lib/model.py:122-141 always ends at 1); anything else fails
        # much later as an opaque reshape error inside the loss or
        # extraction.
        ks, ch = config.ncons_kernel_sizes, config.ncons_channels
        if len(ks) != len(ch):
            raise SystemExit(
                f"{source}: ncons_kernel_sizes ({len(ks)} entries) and "
                f"ncons_channels ({len(ch)}) must be equal length"
            )
        if ch and ch[-1] != 1:
            raise SystemExit(
                f"{source}: ncons_channels must end at 1 (got {tuple(ch)}):"
                " the consensus output is consumed as a single-channel 4-D"
                " correlation tensor"
            )
        return config

    if checkpoint and os.path.isdir(checkpoint):
        restored = load_checkpoint(checkpoint)
        config = restored["config"]
        config = dataclass_replace(
            config,
            relocalization_k_size=relocalization_k_size,
            half_precision=half_precision,
        )
        config = check_consensus_arch(config, f"checkpoint {checkpoint!r}")
        return _with_backbone_dtype(config, backbone_bf16), restored["params"]
    if checkpoint:  # .pth.tar
        params, arch = load_reference_checkpoint(checkpoint)
        config = NCNetConfig(
            backbone=arch["backbone"],
            ncons_kernel_sizes=arch["ncons_kernel_sizes"],
            ncons_channels=arch["ncons_channels"],
            relocalization_k_size=relocalization_k_size,
            half_precision=half_precision,
        )
        config = check_consensus_arch(config, f"checkpoint {checkpoint!r}")
        return _with_backbone_dtype(config, backbone_bf16), params
    config = NCNetConfig(
        backbone=BackboneConfig(cnn=backbone_cnn),
        ncons_kernel_sizes=tuple(ncons_kernel_sizes),
        ncons_channels=tuple(ncons_channels),
        relocalization_k_size=relocalization_k_size,
        half_precision=half_precision,
    )
    config = check_consensus_arch(config, "CLI args")
    config = _with_backbone_dtype(config, backbone_bf16)
    params = ncnet_init(jax.random.PRNGKey(seed), config)
    return config, params


def dataclass_replace(config, **kwargs):
    import dataclasses

    return dataclasses.replace(config, **kwargs)


def to_device(batch: dict) -> dict:
    """Move numpy batch entries onto the default device."""
    return {
        k: jnp.asarray(v) if not isinstance(v, list) else v
        for k, v in batch.items()
    }
