"""PF-Willow keypoint-transfer evaluation CLI (parity: eval_pf_willow.py)."""

from __future__ import annotations

import argparse
import os

from ..data import PFWillowDataset
from .common import build_model
from .eval_pck import evaluate_pck


def main(argv=None):
    parser = argparse.ArgumentParser(description="NCNet-TPU PF-Willow PCK eval")
    parser.add_argument("--checkpoint", type=str, default="")
    parser.add_argument("--image_size", type=int, default=400)
    parser.add_argument(
        "--eval_dataset_path", type=str, default="datasets/pf-willow/"
    )
    parser.add_argument("--csv_file", type=str, default="test_pairs.csv")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_workers", type=int, default=8)
    parser.add_argument("--alpha", type=float, default=0.1)
    args = parser.parse_args(argv)

    config, params = build_model(checkpoint=args.checkpoint)
    dataset = PFWillowDataset(
        os.path.join(args.eval_dataset_path, args.csv_file),
        args.eval_dataset_path,
        output_size=(args.image_size, args.image_size),
    )
    evaluate_pck(config, params, dataset, args.batch_size, args.alpha,
                 num_workers=args.num_workers)


if __name__ == "__main__":
    main()
