"""Shared PCK evaluation harness for PF-Pascal and PF-Willow.

Reference parity: eval_pf_pascal.py / eval_pf_willow.py (identical skeleton).
Unlike the reference (batch_size=1 only, eval_pf_pascal.py:52-53), batches
are supported — keypoints are fixed-size padded, so the whole eval runs as a
handful of jit invocations.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..data import DataLoader
from ..evals import pck_metric
from ..models.ncnet import ncnet_forward
from ..ops import corr_to_matches


def evaluate_pck(
    config,
    params,
    dataset,
    batch_size: int = 8,
    alpha: float = 0.15,
    num_workers: int = 8,
    verbose: bool = True,
):
    """Run keypoint-transfer PCK over a dataset; returns (mean_pck, per_pair)."""

    @jax.jit
    def step(params, source, target, batch_points):
        corr, _ = ncnet_forward(config, params, source, target)
        xa, ya, xb, yb, _ = corr_to_matches(corr, do_softmax=True)
        return pck_metric(batch_points, (xa, ya, xb, yb), alpha)

    loader = DataLoader(
        dataset, batch_size, shuffle=False, num_workers=num_workers
    )
    values = []
    for i, batch in enumerate(loader):
        batch_points = {
            k: jnp.asarray(batch[k])
            for k in (
                "source_points",
                "target_points",
                "source_im_size",
                "target_im_size",
                "L_pck",
            )
        }
        vals = step(
            params,
            jnp.asarray(batch["source_image"]),
            jnp.asarray(batch["target_image"]),
            batch_points,
        )
        values.append(np.asarray(vals))
        if verbose:
            print(f"Batch [{i + 1}/{len(loader)}]", flush=True)

    per_pair = np.concatenate(values)
    good = np.flatnonzero((per_pair != -1) & ~np.isnan(per_pair))
    mean_pck = float(per_pair[good].mean()) if good.size else float("nan")
    if verbose:
        print(f"Total: {per_pair.size}")
        print(f"Valid: {good.size}")
        print(f"PCK: {mean_pck:.2%}")
    return mean_pck, per_pair
