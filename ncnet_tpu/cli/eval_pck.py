"""Shared PCK evaluation harness for PF-Pascal and PF-Willow.

Reference parity: eval_pf_pascal.py / eval_pf_willow.py (identical skeleton).
Unlike the reference (batch_size=1 only, eval_pf_pascal.py:52-53), batches
are supported — keypoints are fixed-size padded, so the whole eval runs as a
handful of jit invocations.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..data import DataLoader
from ..evals import pck_metric
from ..models.ncnet import (
    c2f_coarse_from_features,
    c2f_is_degenerate,
    c2f_raw_matches_from_features,
    extract_features,
    ncnet_forward,
)
from ..ops import corr_to_matches


def evaluate_pck(
    config,
    params,
    dataset,
    batch_size: int = 8,
    alpha: float = 0.15,
    num_workers: int = 8,
    verbose: bool = True,
    bake_params: bool = False,
):
    """Run keypoint-transfer PCK over a dataset; returns (mean_pck, per_pair).

    ``config.mode == 'c2f'`` runs the coarse-to-fine matcher instead of the
    one-shot tensor: the per-B-cell spliced match field feeds the same
    bilinear transfer (row-major over the fine B grid, the contract
    ops.matches.bilinear_point_transfer assumes). Degenerate c2f knobs
    route through the one-shot extraction on the stage-1 tensor, so the
    factor-1/top-K=all setting scores identically to mode='oneshot'.

    ``bake_params`` closes the jit over ``params`` instead of passing
    them as arguments — required for the algebraic consensus arms
    (``config.consensus_kind`` of 'cp'/'fft'), which factorize the
    kernels at trace time and reject tracer weights (ops/cp4d.py).
    """
    use_c2f = getattr(config, "mode", "oneshot") == "c2f"

    def _step(params, source, target, batch_points):
        if not use_c2f:
            corr, _ = ncnet_forward(config, params, source, target)
            xa, ya, xb, yb, _ = corr_to_matches(corr, do_softmax=True)
        else:
            feat_a = extract_features(config, params, source)
            feat_b = extract_features(config, params, target)
            if c2f_is_degenerate(config, feat_a.shape, feat_b.shape):
                corr, _ = c2f_coarse_from_features(
                    config, params, feat_a, feat_b
                )
                xa, ya, xb, yb, _ = corr_to_matches(corr, do_softmax=True)
            else:
                # The c2f machinery is per-pair (static top-K gather);
                # sequential map over the batch keeps one compiled pair
                # program instead of a batch-size family.
                def per_pair(feats):
                    fa, fb = feats
                    return c2f_raw_matches_from_features(
                        config, params, fa[None], fb[None],
                        both_directions=False, invert_direction=False,
                        scale="centered",
                    )

                outs = jax.lax.map(per_pair, (feat_a, feat_b))
                xa, ya, xb, yb, _ = (o[:, 0] for o in outs)
        return pck_metric(batch_points, (xa, ya, xb, yb), alpha)

    if bake_params:
        baked = jax.jit(
            lambda source, target, batch_points: _step(
                params, source, target, batch_points))

        def step(_params, source, target, batch_points):
            return baked(source, target, batch_points)
    else:
        step = jax.jit(_step)

    loader = DataLoader(
        dataset, batch_size, shuffle=False, num_workers=num_workers
    )
    values = []
    for i, batch in enumerate(loader):
        batch_points = {
            k: jnp.asarray(batch[k])
            for k in (
                "source_points",
                "target_points",
                "source_im_size",
                "target_im_size",
                "L_pck",
            )
        }
        vals = step(
            params,
            jnp.asarray(batch["source_image"]),
            jnp.asarray(batch["target_image"]),
            batch_points,
        )
        values.append(np.asarray(vals))
        if verbose:
            print(f"Batch [{i + 1}/{len(loader)}]", flush=True)

    per_pair = np.concatenate(values)
    good = np.flatnonzero((per_pair != -1) & ~np.isnan(per_pair))
    mean_pck = float(per_pair[good].mean()) if good.size else float("nan")
    if verbose:
        print(f"Total: {per_pair.size}")
        print(f"Valid: {good.size}")
        print(f"PCK: {mean_pck:.2%}")
    return mean_pck, per_pair
