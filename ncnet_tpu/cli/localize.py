"""Localization CLI: match files -> poses -> localization-rate curve.

Python-native equivalent of the reference's Matlab driver
(compute_densePE_NCNet.m): consumes the per-query match `.mat` files
written by `cli/eval_inloc.py`, runs P3P LO-RANSAC (and optional dense
pose verification) against the InLoc RGBD cutouts, and writes poses +
the localization-rate curve.

Dataset layout expectations (InLoc): a shortlist `.mat` with an ImgList
struct (queryname / topNname), cutout `.mat` files containing `XYZcut`
(+ optional `RGBcut`), and optionally a ground-truth pose `.mat` for the
final curve.
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from .. import obs
from ..localization import (
    LocalizationParams,
    localization_rate,
    localize_queries,
    plot_localization_curves,
)
from ..localization.curves import DEFAULT_THRESHOLDS
from ..localization.driver import evaluate_poses
from ..utils.py_util import create_file_path


def _load_shortlist(path: str):
    """Parse the InLoc shortlist: {query: [pano, ...]} preserving order."""
    from scipy.io import loadmat

    raw = loadmat(path, squeeze_me=True, struct_as_record=False)
    img_list = raw["ImgList"]
    table = {}
    order = []
    for rec in np.atleast_1d(img_list):
        q = str(rec.queryname)
        table[q] = [str(n) for n in np.atleast_1d(rec.topNname)]
        order.append(q)
    return order, table


def main(argv=None):
    p = argparse.ArgumentParser(description="NCNet-TPU InLoc localization (PnP + curves)")
    p.add_argument("--matches_dir", required=True, help="dir of per-query match .mat files")
    p.add_argument("--shortlist", required=True, help="densePE shortlist .mat")
    p.add_argument("--cutout_dir", required=True, help="InLoc cutout .mat directory")
    p.add_argument("--query_dir", required=True, help="query image directory")
    p.add_argument("--transform_dir", default="", help="scan alignment transformations dir")
    p.add_argument("--output_dir", default="localization_out")
    p.add_argument("--focal_length", type=float, default=4032 * 28.0 / 36.0, help="query focal (px)")
    p.add_argument("--score_thr", type=float, default=0.75)
    p.add_argument("--pnp_thr_deg", type=float, default=0.2)
    p.add_argument("--ransac_iters", type=int, default=10000)
    p.add_argument("--top_n", type=int, default=10)
    p.add_argument("--pose_verification", action="store_true")
    p.add_argument(
        "--num_workers", type=int, default=1,
        help="localize queries concurrently (the reference's Matlab parfor)",
    )
    p.add_argument("--gt_poses", default="", help=".mat/.npz of ground-truth poses for curves")
    p.add_argument(
        "--run_log", default="auto",
        help="run-log JSONL path; 'auto' = <output_dir>/runlog-*.jsonl, '' disables",
    )
    args = p.parse_args(argv)

    os.makedirs(args.output_dir, exist_ok=True)
    run_log = None
    if args.run_log:
        run_log = obs.init_run(
            "localize",
            args.run_log if args.run_log != "auto"
            else obs.default_log_path(args.output_dir, "localize"),
            args=args,
        )

    from scipy.io import loadmat
    from ..data.image_io import read_image

    order, table = _load_shortlist(args.shortlist)

    import functools

    query_index = {q: i for i, q in enumerate(order)}

    # Sized to the worker count: each in-flight query re-reads its match
    # file once per pano if evicted mid-query.
    @functools.lru_cache(maxsize=max(2, 2 * args.num_workers))
    def load_query_matches(q):
        qi = query_index[q] + 1  # match files are written 1-indexed per query
        return np.asarray(loadmat(os.path.join(args.matches_dir, f"{qi}.mat"))["matches"])

    def load_matches(q, j):
        return load_query_matches(q)[0, j, :, :5]

    def load_cutout(pano):
        raw = loadmat(os.path.join(args.cutout_dir, pano + ".mat"))
        xyz = np.asarray(raw["XYZcut"], dtype=np.float64)
        rgb = np.asarray(raw["RGBcut"], dtype=np.float64) if "RGBcut" in raw else None
        transform = None
        if args.transform_dir:
            # InLoc naming: <building>/transformations/<scene>_trans_<scan>.txt
            # where cutouts look like '<bldg>/cutout_<scan>_<pan>_<tilt>.jpg':
            # scene id = token before 'cutout', scan id = first numeric token
            # after it.
            floor = pano.split("/")[0]
            base = os.path.basename(pano)
            while os.path.splitext(base)[1]:
                base = os.path.splitext(base)[0]
            tokens = base.split("_")
            scene_id = tokens[0] if tokens[0] != "cutout" else floor
            numeric = [t for t in tokens if t.isdigit()]
            scan_id = numeric[0] if numeric else ""
            tpath = os.path.join(
                args.transform_dir, floor, "transformations",
                f"{scene_id}_trans_{scan_id}.txt",
            )
            if os.path.exists(tpath):
                rows = [
                    [float(v) for v in line.split()]
                    for line in open(tpath)
                    if line.strip() and not line[0].isalpha()
                ]
                transform = np.asarray(rows[-4:], dtype=np.float64)
            else:
                print(f"WARNING: no scan transform at {tpath}; using local frame", flush=True)
        if rgb is not None:
            return xyz, transform, rgb
        return xyz, transform

    def query_size(q):
        img = read_image(os.path.join(args.query_dir, q))
        return img.shape[0], img.shape[1]

    def load_query_image(q):
        return read_image(os.path.join(args.query_dir, q))

    params = LocalizationParams(
        score_thr=args.score_thr,
        pnp_thr_deg=args.pnp_thr_deg,
        ransac_iters=args.ransac_iters,
        top_n=args.top_n,
        use_pose_verification=args.pose_verification,
    )
    try:
        results = localize_queries(
            order,
            shortlist=lambda q: table[q],
            load_matches=load_matches,
            load_cutout=load_cutout,
            query_size=query_size,
            focal_length=args.focal_length,
            params=params,
            cache_dir=os.path.join(args.output_dir, "pnp_cache"),
            load_query_image=load_query_image if args.pose_verification else None,
            progress=lambda q: print(f"localized: {q}", flush=True),
            num_workers=args.num_workers,
        )
    except BaseException as exc:
        if run_log is not None:
            run_log.close(f"error:{type(exc).__name__}")
            run_log = None
        raise

    poses_path = os.path.join(args.output_dir, "poses.npz")
    create_file_path(poses_path)
    np.savez(
        poses_path,
        queries=np.array([r.query for r in results]),
        poses=np.stack([r.best_pose for r in results]),
        num_inliers=np.array(
            [r.num_inliers[r.best_index] if r.best_index >= 0 else 0 for r in results]
        ),
    )
    print(f"wrote {poses_path}")

    summary = None
    if args.gt_poses:
        if args.gt_poses.endswith(".npz"):
            with np.load(args.gt_poses, allow_pickle=True) as z:
                gt = {str(q): P for q, P in zip(z["queries"], z["poses"])}
        else:
            raw = loadmat(args.gt_poses, squeeze_me=True, struct_as_record=False)
            # Merge EVERY RefList variable: the reference's GT file
            # (lib_matlab/DUC_refposes_all.mat) splits the 329 poses over
            # DUC1_RefList + DUC2_RefList — reading one key would
            # silently score only one building.
            gt = {}
            for key in raw:
                if key.startswith("__"):
                    continue
                for r in np.atleast_1d(raw[key]):
                    gt[str(r.queryname)] = np.asarray(r.P)
        pos_e, ori_e = evaluate_poses(results, gt)
        rates = localization_rate(pos_e, ori_e)
        curve_png = os.path.join(args.output_dir, "localization_curve.png")
        plot_localization_curves({"NCNet-TPU densePE": rates}, curve_png)
        summary = {
            "rate@0.25m": float(rates[np.searchsorted(DEFAULT_THRESHOLDS, 0.25)]),
            "rate@0.5m": float(rates[np.searchsorted(DEFAULT_THRESHOLDS, 0.5)]),
            "rate@1.0m": float(rates[np.searchsorted(DEFAULT_THRESHOLDS, 1.0)]),
            "n_queries": len(results),
        }
        print(json.dumps(summary))
        print(f"wrote {curve_png}")
    if run_log is not None:
        n_unsolved = sum(1 for r in results if r.best_index < 0)
        run_log.event("localization_summary", n_queries=len(results),
                      n_unsolved=n_unsolved, summary=summary)
        run_log.flush_metrics(phase="localization")
        run_log.close("ok", n_queries=len(results))
    return summary


if __name__ == "__main__":
    main()
