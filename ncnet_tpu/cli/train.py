"""Weak-supervision training CLI.

Usage (defaults reproduce the reference's published PF-Pascal run,
train.py:34-49 of the reference tree):

    python -m ncnet_tpu.cli.train --dataset_image_path datasets/pf-pascal \
        --dataset_csv_path datasets/pf-pascal/image_pairs

Data parallelism: the batch is sharded over all available devices on a 'dp'
mesh; the jitted step contains both forward passes and the Adam update, and
XLA inserts the gradient allreduce over ICI.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..data import ImagePairDataset, DataLoader
from ..parallel import make_mesh, multihost
from ..parallel.membership import MembershipPlane
from ..reliability import failpoints
from ..training import (
    create_train_state,
    elastic as elastic_mod,
    load_latest_checkpoint,
    load_opt_state,
    make_train_step,
    resolve_resume_dir,
    save_checkpoint,
    shard_batch,
    replicate_state,
)
from .common import build_model


def main(argv=None):
    parser = argparse.ArgumentParser(description="NCNet-TPU weak-supervision training")
    parser.add_argument("--checkpoint", type=str, default="")
    parser.add_argument("--image_size", type=int, default=400)
    parser.add_argument("--dataset_image_path", type=str, default="datasets/pf-pascal/")
    parser.add_argument(
        "--dataset_csv_path", type=str, default="datasets/pf-pascal/image_pairs/"
    )
    parser.add_argument("--num_epochs", type=int, default=5)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--ncons_kernel_sizes", nargs="+", type=int, default=[5, 5, 5])
    parser.add_argument("--ncons_channels", nargs="+", type=int, default=[16, 16, 1])
    parser.add_argument("--backbone", type=str, default="resnet101")
    parser.add_argument("--result_model_dir", type=str, default="trained_models")
    parser.add_argument("--result_model_fn", type=str, default="checkpoint_adam")
    parser.add_argument("--fe_finetune_params", type=int, default=0)
    # Recompute backbone activations in the backward pass (HBM lever for
    # fine-tuning at high resolution / large batch).
    parser.add_argument("--remat_backbone", action="store_true", default=False)
    # Gradient accumulation over N sequential micro-batches: only one
    # micro-batch of AD activations is live at a time (lax.scan), the HBM
    # lever for the reference's batch-16 schedule. Negatives roll within
    # each micro-batch (see make_train_step). batch_size must divide by N.
    parser.add_argument("--grad_accum", type=int, default=1)
    parser.add_argument("--num_workers", type=int, default=8)
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--log_interval", type=int, default=1)
    parser.add_argument(
        "--run_log", type=str, default="auto",
        help="structured JSONL run log (docs/OBSERVABILITY.md): 'auto' "
        "writes runlog-train-<stamp>.jsonl into the run's checkpoint "
        "dir (host 0 only), a path writes there, empty disables",
    )
    # Preemption story (SURVEY §5): --save_interval N writes a rolling
    # mid-epoch checkpoint (tag "step") every N steps; --resume continues
    # a --checkpoint run from its recorded (epoch, step) instead of from
    # epoch 1 — the loader's per-epoch shuffle is a pure function of
    # (seed, epoch), so the exact batch schedule replays and the first
    # `step` batches of the resumed epoch are skipped.
    parser.add_argument("--save_interval", type=int, default=0,
                        help="steps between rolling mid-epoch checkpoints "
                        "(0 = per-epoch only)")
    parser.add_argument("--resume", action="store_true", default=False,
                        help="resume epoch/step position from --checkpoint")
    parser.add_argument(
        "--profile_dir", type=str, default="",
        help="capture a jax.profiler trace of the run for TensorBoard/Perfetto",
    )
    # Training observatory (docs/OBSERVABILITY.md "Training
    # observatory"): the divergence sentinel resolves loss/grad-norm a
    # few steps late (never a same-step sync) and applies this policy
    # on NaN/inf or sustained grad-norm drift.
    parser.add_argument(
        "--on_divergence", type=str, default="halt",
        choices=list(obs.train_watch.POLICIES),
        help="divergence policy: halt raises after the train-divergence "
        "flight dump, skip drops the offending steps from the epoch "
        "average and continues, dump-only records and continues",
    )
    parser.add_argument(
        "--step_timeout_s", type=float, default=0.0,
        help="hard per-step watchdog: a device step hung past this many "
        "seconds flight-dumps and exits (0 disables)",
    )
    # Elastic membership (docs/RELIABILITY.md "Elastic training
    # membership"): hosts rendezvous through lease files under
    # --elastic_dir; when a peer goes silent past the lease TTL the
    # survivors bump the generation, reload the last committed
    # checkpoint, re-derive their batch slices for the reduced host
    # set, and continue.
    parser.add_argument(
        "--elastic_dir", type=str, default="",
        help="filesystem membership root shared by the gang (empty "
        "disables elastic mode)")
    parser.add_argument(
        "--elastic_host", type=str, default="",
        help="this host's membership name (default: multihost.host_label())")
    parser.add_argument(
        "--elastic_hosts", type=str, default="",
        help="comma-separated initial gang; the first host to form the "
        "generation record wins, later hosts join it")
    parser.add_argument(
        "--lease_ttl_s", type=float, default=5.0,
        help="membership lease TTL: a host silent this long is declared "
        "dead and evicted by the survivors")
    args = parser.parse_args(argv)

    if args.grad_accum < 1:
        raise SystemExit("--grad_accum must be >= 1")
    if args.grad_accum > 1 and (
        args.batch_size % args.grad_accum
        or args.batch_size // args.grad_accum < 2
    ):
        raise SystemExit(
            f"--grad_accum {args.grad_accum} needs batch_size "
            f"{args.batch_size} divisible by it with a micro-batch >= 2 "
            "(the weak loss rolls negatives within a micro-batch)"
        )

    # --resume must tolerate a preemption INSIDE save_checkpoint's
    # rename-aside swap: the complete checkpoint may sit at the sibling
    # step.tmp / step.old instead of the dir the user named. Resolve
    # before ANY use of args.checkpoint (build_model reads it first).
    if args.resume and args.checkpoint:
        resolved = resolve_resume_dir(args.checkpoint)
        if resolved is None:
            raise SystemExit(
                f"--resume: no complete checkpoint at {args.checkpoint} "
                "(also tried .tmp/.old siblings)"
            )
        if resolved != os.path.normpath(args.checkpoint):
            print(f"resume: swap was interrupted; using {resolved}")
        args.checkpoint = resolved

    # Multi-host bootstrap: a no-op unless a coordinator is configured in
    # the environment (JAX_COORDINATOR_ADDRESS etc., see parallel.multihost).
    # After it, jax.devices() is the GLOBAL device list and the same program
    # runs unchanged on every host.
    multihost.initialize()

    # Elastic membership plane: form/join the gang BEFORE any heavy
    # setup so the lease heartbeat covers model build and jit compile
    # (peers must not declare this host dead while it compiles).
    driver = None
    if args.elastic_dir:
        host_id = args.elastic_host or multihost.host_label()
        gang = sorted(
            {h.strip() for h in args.elastic_hosts.split(",") if h.strip()}
            | {host_id}
        )
        plane = MembershipPlane(
            args.elastic_dir, host_id, lease_ttl_s=args.lease_ttl_s)
        plane.form(gang)
        # Rejoin after eviction: a previously-dead host finding itself
        # outside the current generation admits itself via a grow bump
        # at the CURRENT generation; peers pick the new record up as a
        # MembershipChange at their next step_check.
        while True:
            rec = plane.read_generation()
            if rec is None or host_id in rec["hosts"]:
                break
            plane.bump(
                sorted(set(rec["hosts"]) | {host_id}),
                resume_epoch=rec.get("resume_epoch", 1),
                resume_step=rec.get("resume_step", 0),
                expected_generation=rec["generation"],
            )
        driver = elastic_mod.ElasticDriver(
            plane, ledger_dir=args.elastic_dir)
        driver.start()

    print("NCNet-TPU training")
    print(args)

    config, params = build_model(
        checkpoint=args.checkpoint,
        ncons_kernel_sizes=tuple(args.ncons_kernel_sizes),
        ncons_channels=tuple(args.ncons_channels),
        backbone_cnn=args.backbone,
        seed=args.seed,
    )

    # --fe_finetune_params N fine-tunes the backbone's last N blocks, as in
    # the reference (lib/model.py:75-78 unfreezes the last N parameter
    # groups); N=0 keeps the backbone frozen.
    state, tx = create_train_state(
        params,
        learning_rate=args.lr,
        train_fe=args.fe_finetune_params > 0,
        fe_finetune_blocks=max(args.fe_finetune_params, 1),
    )
    # Resume the optimizer state alongside the params (the reference saves
    # it but never restores it, train.py:203 — a defect not replicated).
    # load_opt_state reads only opt_state.npz (params were already restored
    # by build_model) and raises a clear error on an optimizer mismatch.
    restored_opt = None
    restore_err = None
    if args.checkpoint and os.path.isdir(args.checkpoint):
        try:
            restored_opt = load_opt_state(args.checkpoint, state.opt_state)
        except Exception as exc:  # noqa: BLE001 — re-raised below, after the
            # collective: a host raising here BEFORE the allgather would
            # leave its peers blocked in the collective forever.
            restore_err = exc
        if restored_opt is not None:
            state.opt_state = restored_opt
            print(f"restored optimizer state from {args.checkpoint}")
    # Multi-host: without a shared filesystem, the checkpoint dir (or just
    # opt_state.npz) may exist on only some hosts — host 0 would resume Adam
    # moments while others start fresh, silently diverging the replicated
    # state. Fail loudly on partial restoration instead. The allgather is a
    # collective, so it must run on EVERY host — unconditionally of whether
    # this host found the directory (args.checkpoint itself is identical
    # across hosts: same command line everywhere).
    if args.checkpoint and multihost.process_count() > 1:
        from jax.experimental import multihost_utils

        # -1 = restore raised, 0 = no opt state found, 1 = restored.
        status = -1 if restore_err is not None else int(restored_opt is not None)
        flags = multihost_utils.process_allgather(jnp.int32(status))
        if int(flags.min()) != int(flags.max()):
            raise SystemExit(
                "optimizer-state restore disagrees across hosts "
                f"(per-host status, -1=error 0=absent 1=restored: "
                f"{list(map(int, flags))}); make the checkpoint directory "
                "visible to every host or remove opt_state.npz everywhere"
            ) from restore_err
    if restore_err is not None:
        raise restore_err
    train_step, eval_step = make_train_step(
        config, tx, remat_backbone=args.remat_backbone,
        accum_steps=args.grad_accum,
    )

    # Use the largest device count that divides the MICRO-batch (the unit
    # each scan step of a grad-accumulated run actually shards; requiring
    # only full-batch divisibility would make GSPMD reshard/pad inside
    # every accumulation step). Multi-host requires the full global device
    # count to divide it.
    n_proc = multihost.process_count()
    n_dev = len(jax.devices())
    # Elastic mode trains the largest batch the LIVE host count divides
    # (round down + train_batch_adjusted event) instead of aborting.
    global_batch = args.batch_size
    if driver is not None:
        global_batch = elastic_mod.adjusted_global_batch(
            args.batch_size, driver.n_hosts)
    # Rows that flow through THIS process's device grid per step: in
    # elastic harness mode (one JAX process per host) that is the
    # membership-derived slice, not the global batch.
    local_rows = (
        global_batch // driver.n_hosts
        if driver is not None and n_proc == 1
        else global_batch
    )
    micro = local_rows // max(args.grad_accum, 1)
    if n_proc > 1:
        if micro % n_dev:
            raise SystemExit(
                f"multi-host run: micro-batch {micro} (batch_size "
                f"{args.batch_size} / grad_accum {args.grad_accum}) must "
                f"be divisible by the global device count {n_dev}"
            )
    else:
        while n_dev > 1 and micro % n_dev:
            n_dev -= 1
    mesh = make_mesh((n_dev,), ("dp",)) if n_dev > 1 else None
    if mesh is not None:
        state = replicate_state(state, mesh)
    print(
        f"devices: {len(jax.devices())} (dp axis: {n_dev}, hosts: {n_proc})"
    )

    # Each host decodes only its slice of every (deterministically
    # scheduled) global batch and contributes it to the global array.
    if n_proc > 1:
        batch_slice = multihost.host_local_slice(global_batch)
        put = lambda b: multihost.host_local_batch(b, mesh)  # noqa: E731
    elif driver is not None and driver.n_hosts > 1:
        # Elastic harness mode: each host trains its generation-derived
        # slice on its own device grid (gradient exchange, if any, is
        # the launcher's concern — see training/elastic.py docstring).
        batch_slice = driver.slice_for(global_batch)
        put = lambda b: shard_batch(b, mesh)  # noqa: E731
    else:
        batch_slice = None
        put = lambda b: shard_batch(b, mesh)  # noqa: E731

    size = (args.image_size, args.image_size)
    dataset = ImagePairDataset(
        os.path.join(args.dataset_csv_path, "train_pairs.csv"),
        args.dataset_image_path,
        output_size=size,
        rng=np.random.RandomState(args.seed),
    )
    dataset_val = ImagePairDataset(
        os.path.join(args.dataset_csv_path, "val_pairs.csv"),
        args.dataset_image_path,
        output_size=size,
    )
    if global_batch > len(dataset):
        raise SystemExit(
            f"batch_size {global_batch} exceeds dataset size {len(dataset)}; "
            "with drop_last this would train on zero batches"
        )
    loader = DataLoader(
        dataset, global_batch, shuffle=True, num_workers=args.num_workers,
        seed=args.seed, drop_last=True, batch_slice=batch_slice,
    )
    if global_batch > len(dataset_val):
        print(
            f"WARNING: batch_size {global_batch} exceeds val-set size "
            f"{len(dataset_val)}; validation will see zero batches, so the "
            "best checkpoint is selected by train loss instead",
            flush=True,
        )
    loader_val = DataLoader(
        dataset_val, global_batch, shuffle=False,
        num_workers=args.num_workers, drop_last=True, batch_slice=batch_slice,
    )

    if driver is not None:
        # Elastic mode: every host must agree on the checkpoint chain
        # (survivors resume from whatever the writer last committed),
        # so the run dir is pinned by name, not timestamp-claimed.
        ckpt_dir = os.path.join(args.result_model_dir, args.result_model_fn)
        os.makedirs(ckpt_dir, exist_ok=True)
    else:
        # Claim the run directory ATOMICALLY at launch (exist_ok=False):
        # checkpoints are otherwise written lazily at end of epoch, so two
        # runs started the same minute would silently interleave into one
        # dir. Host 0 claims; other hosts never write (see _epoch_loop).
        suffix = 0
        while True:
            name = time.strftime("%Y-%m-%d_%H%M") + "_" + args.result_model_fn
            if suffix:
                name += f"_{suffix + 1}"
            ckpt_dir = os.path.join(args.result_model_dir, name)
            if multihost.process_index() != 0:
                break
            try:
                os.makedirs(ckpt_dir, exist_ok=False)
                break
            except FileExistsError:
                suffix += 1

    # Checkpoint ownership: rank 0 of the live generation in elastic
    # mode (writer takeover on a shrink is automatic), process 0
    # otherwise. Params/losses are replicated, so exactly one host
    # writes the chain.
    writer = (driver.is_writer if driver is not None
              else multihost.process_index() == 0)

    # Telemetry on the writer only — except elastic mode, where every
    # host keeps its OWN runlog (hosts share ckpt_dir; the chaos audit
    # reads each host's beacons and the writer's curve).
    run_log = None
    if args.run_log and (driver is not None
                         or multihost.process_index() == 0):
        if args.run_log != "auto":
            log_path = args.run_log
        elif driver is not None:
            log_path = os.path.join(
                ckpt_dir, f"runlog-train-{driver.plane.host}.jsonl")
        else:
            log_path = obs.default_log_path(ckpt_dir, "train")
        run_log = obs.init_run("train", log_path, args=args)
        run_log.event(
            "devices",
            n_devices=len(jax.devices()),
            platform=jax.devices()[0].platform,
            ckpt_dir=ckpt_dir,
        )

    # --resume: continue from the checkpoint's recorded position. A
    # mid-epoch ("step") checkpoint carries step_in_epoch; a per-epoch one
    # means that epoch COMPLETED, so resumption starts at the next.
    start_epoch, skip_steps, resume_meta = 1, 0, None
    if args.resume:
        if not (args.checkpoint and os.path.isdir(args.checkpoint)):
            raise SystemExit("--resume requires --checkpoint <dir>")
        with open(os.path.join(args.checkpoint, "meta.json")) as f:
            resume_meta = json.load(f)
        if "step_in_epoch" in resume_meta:
            start_epoch = int(resume_meta["epoch"])
            skip_steps = int(resume_meta["step_in_epoch"])
        else:
            start_epoch = int(resume_meta["epoch"]) + 1
        print(f"resuming at epoch {start_epoch}, step {skip_steps}")
        # Multi-host: resolve_resume_dir runs per host against per-host
        # filesystems, so hosts caught at different points of the rolling
        # swap could silently resume from DIFFERENT checkpoints (the
        # opt-state guard above only compares restore status). Compare
        # the resolved position itself and fail loudly on divergence.
        if multihost.process_count() > 1:
            from jax.experimental import multihost_utils

            pos = multihost_utils.process_allgather(
                jnp.array([start_epoch, skip_steps], jnp.int32)
            )
            if not bool((pos == pos[0]).all()):
                raise SystemExit(
                    "resume position disagrees across hosts (per-host "
                    f"[epoch, step]: {pos.tolist()}); the rolling-swap "
                    "siblings resolved differently — make the SAME "
                    "checkpoint state visible to every host"
                )
        # Carry the best/ checkpoint into the new run dir: best_val
        # resumes from meta, so if no post-resume epoch beats it the new
        # run would otherwise end with NO best/ at all (the true best
        # stranded in the abandoned pre-preemption dir).
        if multihost.process_index() == 0:
            # resolve_resume_dir doubles as the completeness check here:
            # best/ uses the same rename-aside swap, so a preemption
            # mid-swap may have left the complete copy at a .tmp/.old
            # sibling — and a partial dir must not be carried.
            best_src = resolve_resume_dir(os.path.join(
                os.path.dirname(os.path.normpath(args.checkpoint)), "best"
            ))
            best_dst = os.path.join(ckpt_dir, "best")
            if best_src and not os.path.exists(best_dst):
                from ..training.checkpoint import copy_checkpoint_dir

                copy_checkpoint_dir(best_src, best_dst)
                print(f"resume: carried best checkpoint from {best_src}")
                # Old-format step metas lack best_val_loss; without a
                # threshold the first post-resume epoch would overwrite
                # the carried best/ unconditionally (inf comparison).
                # Seed it from the carried best's own meta.
                if "best_val_loss" not in resume_meta:
                    try:
                        with open(os.path.join(best_src, "meta.json")) as f:
                            best_meta = json.load(f)
                        seed_val = best_meta.get("best_val_loss")
                        if seed_val is None:
                            # e.g. best/ written by convert_checkpoint
                            # (extra=None): fall back to its loss curve.
                            curve = best_meta.get("val_loss") or []
                            seed_val = min(curve) if curve else None
                        if seed_val is not None:
                            resume_meta["best_val_loss"] = float(seed_val)
                        else:
                            print(
                                "resume: warning: carried best/ records no "
                                "loss; the first post-resume epoch will "
                                "replace it"
                            )
                    except (OSError, ValueError) as exc:
                        print(
                            "resume: warning: could not seed best_val "
                            f"from carried best/ ({exc})"
                        )

    from ..utils.profiling import trace_context

    try:
        with trace_context(args.profile_dir):
            while True:
                try:
                    _epoch_loop(args, config, state, train_step, eval_step,
                                loader, loader_val, put, ckpt_dir,
                                start_epoch=start_epoch,
                                skip_steps=skip_steps,
                                resume_meta=resume_meta, driver=driver,
                                writer=writer)
                    if driver is not None and driver.n_hosts > 1:
                        # An early finisher's expiring lease must not
                        # read as a mid-run death to peers still
                        # training (they would bump and replay the
                        # tail epoch for nothing).
                        driver.finish_barrier(args.num_epochs)
                    break
                except elastic_mod.MembershipChange as chg:
                    if multihost.process_count() > 1:
                        # jax.distributed cannot reshape a live process
                        # set: the generation bump is already durable,
                        # so exit and let the launcher re-form the gang
                        # (survivors resume from the same checkpoint
                        # chain at the new generation).
                        raise SystemExit(
                            "membership changed (generation "
                            f"{chg.record.get('generation')}, hosts "
                            f"{chg.record.get('hosts')}): relaunch to "
                            "re-form the gang"
                        )
                    (loader, loader_val, start_epoch, skip_steps,
                     resume_meta, writer) = _elastic_resume(
                        args, chg, driver, state, ckpt_dir,
                        dataset, dataset_val, len(loader))
    except BaseException as exc:
        if run_log is not None:
            run_log.close(f"error:{type(exc).__name__}")
        raise
    finally:
        if driver is not None:
            driver.stop()
    if run_log is not None:
        run_log.close("ok")
    print("Done!")


def _elastic_resume(args, chg, driver, state, ckpt_dir, dataset, dataset_val,
                    steps_per_epoch):
    """Adopt a new generation in-process: reload the last committed
    checkpoint (fallback walk), re-derive this host's batch slice for
    the live host set, rebuild the loaders, and hand back the position
    the epoch loop re-enters at."""
    path, loaded = load_latest_checkpoint(
        ckpt_dir, opt_state_template=state.opt_state)
    meta = loaded["meta"]
    if "step_in_epoch" in meta:
        r_epoch, r_step = int(meta["epoch"]), int(meta["step_in_epoch"])
    else:
        r_epoch, r_step = int(meta["epoch"]) + 1, 0
    det_epoch = chg.epoch if chg.epoch is not None else r_epoch
    det_step = chg.step if chg.step is not None else r_step
    driver.resume(chg.record, r_epoch, r_step, det_epoch, det_step,
                  steps_per_epoch=steps_per_epoch)
    print(
        f"elastic: generation {driver.generation} hosts {driver.hosts}"
        + (f" (dead: {chg.dead})" if chg.dead else "")
        + f"; resuming from {path} at epoch {r_epoch}, step {r_step}",
        flush=True,
    )
    # Restore params/opt state IN PLACE: the jitted train_step closed
    # over the original optimizer, and the reloaded opt_state has the
    # same tree structure (load_opt_state enforces it).
    fresh, _tx = create_train_state(
        loaded["params"],
        learning_rate=args.lr,
        train_fe=args.fe_finetune_params > 0,
        fe_finetune_blocks=max(args.fe_finetune_params, 1),
    )
    state.trainable = fresh.trainable
    state.frozen = fresh.frozen
    state.opt_state = loaded.get("opt_state", fresh.opt_state)
    # The shrunk host count may no longer divide the old batch: re-round
    # and rebuild the loaders with this generation's slice. The loader
    # schedule stays a pure function of (seed, epoch), so every survivor
    # replays the identical batch sequence.
    global_batch = elastic_mod.adjusted_global_batch(
        args.batch_size, driver.n_hosts)
    batch_slice = (driver.slice_for(global_batch)
                   if driver.n_hosts > 1 else None)
    loader = DataLoader(
        dataset, global_batch, shuffle=True, num_workers=args.num_workers,
        seed=args.seed, drop_last=True, batch_slice=batch_slice,
    )
    loader_val = DataLoader(
        dataset_val, global_batch, shuffle=False,
        num_workers=args.num_workers, drop_last=True,
        batch_slice=batch_slice,
    )
    # A per-epoch checkpoint means that epoch COMPLETED.
    start_epoch, skip_steps = (
        (r_epoch, r_step) if "step_in_epoch" in meta else (r_epoch, 0))
    return (loader, loader_val, start_epoch, skip_steps, meta,
            driver.is_writer)


def _epoch_loop(args, config, state, train_step, eval_step, loader, loader_val,
                put_batch, ckpt_dir, start_epoch: int = 1,
                skip_steps: int = 0, resume_meta=None, driver=None,
                writer=None):
    from ..data.loader import device_prefetch

    if writer is None:
        writer = multihost.process_index() == 0

    # Restore the loss history and best-checkpoint threshold from the
    # resumed checkpoint's meta so a resume does not silently reset them
    # (a fresh best_val=inf would let the first post-resume epoch steal
    # "best" regardless of the pre-preemption record).
    best_val = float("inf")
    train_losses, val_losses = [], []
    resumed_epoch_losses = []
    if resume_meta is not None:
        train_losses = [float(x) for x in resume_meta.get("train_loss", [])]
        val_losses = [float(x) for x in resume_meta.get("val_loss", [])]
        best_val = float(resume_meta.get("best_val_loss", float("inf")))
        # Per-step losses of the partially-trained epoch: the resumed
        # epoch's train_loss must average ALL its batches, not just the
        # post-resume ones, and an exactly-at-the-boundary checkpoint
        # (step_in_epoch == len(loader)) must still run validation and
        # the per-epoch save for that epoch instead of recording 0.0.
        resumed_epoch_losses = [
            float(x) for x in resume_meta.get("epoch_losses", [])
        ]
    if skip_steps >= len(loader) and not resumed_epoch_losses:
        # Old-format step checkpoint (no epoch_losses) at the exact
        # boundary: the epoch is complete but its per-step losses are
        # gone — skip into the next epoch rather than recording a
        # zero-batch epoch whose 0.0 train_loss would drive
        # best-checkpoint selection.
        start_epoch += 1
        skip_steps = 0
        if start_epoch > args.num_epochs:
            print(
                f"resume: checkpoint already covers all {args.num_epochs} "
                "epochs (its per-step losses predate the epoch_losses "
                "format, so the final epoch's validation cannot be "
                "reconstructed); nothing to train"
            )
    trainable, opt_state = state.trainable, state.opt_state
    # Fast-forward the loader's epoch counter so epoch E shuffles with
    # RandomState(seed + E - 1) exactly as the original run did.
    loader.set_epoch(start_epoch - 1)

    def put(batch):
        out = put_batch(
            {k: batch[k] for k in ("source_image", "target_image")}
        )
        # Manifest ids stay HOST-side (never device-put): the
        # divergence sentinel's ring names offending batches by them.
        if "_indices" in batch:
            out["_indices"] = np.asarray(batch["_indices"])
        return out

    # Training observatory: per-step telemetry + span trees, the
    # bounded-lag divergence sentinel, per-host step beacons, and the
    # optional per-step watchdog (obs/train_watch.py). Dumps land next
    # to the run log when one is active.
    run_path = getattr(obs.get_run(), "path", None)
    watch = obs.train_watch.TrainWatch(
        policy=args.on_divergence,
        lr=args.lr,
        log_interval=args.log_interval,
        # Elastic harness mode: the membership name IS the replica
        # label (every process is JAX process 0, so host_label() would
        # collide all hosts onto "host0" in a fleet merge).
        host=(driver.plane.host if driver is not None
              else multihost.host_label()),
        step_timeout_s=args.step_timeout_s,
        flight_dir=os.path.dirname(os.path.abspath(run_path))
        if run_path else None,
    )

    for epoch in range(start_epoch, args.num_epochs + 1):
        t0 = time.time()
        # The resumed epoch starts with the losses of its already-trained
        # batches so train_loss averages the WHOLE epoch.
        losses = list(resumed_epoch_losses) if epoch == start_epoch else []
        n_preloaded = len(losses)
        # Resumed epoch: replay the deterministic schedule; the
        # generator drops already-trained batches before the device
        # transfer (the loader still decodes them, backpressured by
        # its prefetch queue — minutes at worst for a full epoch).
        skip = skip_steps if epoch == start_epoch else 0

        def resumed(it=loader, skip=skip, epoch=epoch):
            if skip >= len(it):
                # Exact-boundary resume: every batch is already trained.
                # Don't decode the whole epoch just to drop it — position
                # the shuffle schedule where a real iteration of epoch
                # `epoch` would have left it (the NEXT iteration shuffles
                # with seed + epoch) and go straight to validation.
                it.set_epoch(epoch)
                return
            for j, b in enumerate(it):
                if j >= skip:
                    yield b

        # One batch in flight: H2D transfer of batch i+1 overlaps step i.
        # Losses stay DEVICE scalars inside the loop — float() would force a
        # full sync every step, serializing dispatch; on a tunneled backend
        # that costs a round trip per batch. The sync happens only at log
        # points (per batch at the default --log_interval 1, matching the
        # reference's per-batch print; raise it to unlock async dispatch)
        # and in the sentinel, which resolves values a few steps old.
        watch.reset_epoch()
        for i, batch in watch.steps(
            device_prefetch(resumed(), put), start=skip
        ):
            # Chaos plant (docs/RELIABILITY.md): error/delay fire here,
            # pre-dispatch; the corrupt mode is consumed downstream by
            # the sentinel's loss resolve in obs/train_watch.py.
            failpoints.fire("train.step", payload=i)
            if driver is not None:
                # Membership probe (time-gated; a dict read most steps).
                # Raises MembershipChange — main() reloads the last
                # committed checkpoint and re-enters this loop.
                driver.step_check(epoch, i)
            trainable, opt_state, loss, aux = train_step(
                trainable, state.frozen, opt_state,
                batch["source_image"], batch["target_image"],
            )
            # Books step-time/data-wait histograms, the train.step span
            # tree, the step beacon, and queues loss/grad-norm for the
            # bounded-lag divergence check (may raise TrainDivergence
            # under --on_divergence halt).
            watch.book(
                epoch=epoch, step=i, loss=loss,
                grad_norm=aux["grad_norm"],
                update_ratio=aux["update_ratio"],
                batch_ids=batch.get("_indices"),
            )
            if i % args.log_interval == 0:
                loss = float(loss)  # the only fetch of this scalar
                print(
                    f"Train epoch {epoch} [{i}/{len(loader)}]\tloss: "
                    f"{loss:.6f}",
                    flush=True,
                )
            losses.append(loss)
            if driver is not None:
                # Step ledger: the zero-silent-step-loss audit replays
                # these lines per generation (tools/chaos_train.py).
                driver.record_step(
                    epoch, i,
                    loader.batch_slice or (0, loader.batch_size))
            if (
                args.save_interval
                and (i + 1) % args.save_interval == 0
                and writer
                # Elastic gangs: only commit a position every live
                # member's lease shows reached (a dead host must not
                # leave its share of the post-commit steps untrained —
                # see ElasticDriver.commit_barrier).
                and (driver is None or driver.n_hosts == 1
                     or driver.commit_barrier(epoch, i + 1))
            ):
                # Fetch each device scalar at most once across all saves
                # (with --log_interval > 1 most entries are still device
                # scalars; re-converting the whole list every save would
                # be O(steps^2 / save_interval) tunnel round trips).
                losses[:] = [
                    l if isinstance(l, float) else float(l) for l in losses
                ]
                full_params = {
                    "backbone": trainable.get(
                        "backbone", state.frozen["backbone"]
                    ),
                    "neigh_consensus": trainable["neigh_consensus"],
                }
                save_checkpoint(
                    ckpt_dir, full_params, config, epoch,
                    opt_state=opt_state,
                    # Completed-epoch history + this epoch's per-step
                    # losses ride along so a resume restores best_val,
                    # the loss curves, AND can finish this epoch with a
                    # correctly-averaged train_loss (ADVICE r3).
                    # best_val is inf until a validation has run; omit
                    # it then (json would emit non-RFC 'Infinity') —
                    # the resume path already .get()s with an inf
                    # default.
                    extra={"step_in_epoch": i + 1, "args": vars(args),
                           "train_loss": train_losses,
                           "val_loss": val_losses,
                           **({"best_val_loss": best_val}
                              if best_val != float("inf") else {}),
                           "epoch_losses": losses},
                    tag="step",
                )
                if driver is not None:
                    driver.note_commit(epoch, i + 1)
        # Resolve the sentinel's tail before averaging: the last `lag`
        # steps' losses must still pass the divergence check.
        watch.drain()
        loss_vals = [float(l) for l in losses]
        if watch.policy == "skip":
            # skip policy: divergent steps are dropped from the curve
            # (a NaN would otherwise poison the epoch mean and every
            # downstream best-checkpoint comparison) — the run records
            # the skip and keeps training.
            n_bad = sum(1 for v in loss_vals if not math.isfinite(v))
            if n_bad:
                obs.event("train_divergence_skipped", epoch=epoch,
                          n_skipped=n_bad)
                loss_vals = [v for v in loss_vals if math.isfinite(v)]
        train_loss = float(np.mean(loss_vals)) if loss_vals else 0.0
        train_dt = time.time() - t0

        val_loss, n_val = 0.0, 0
        for batch in loader_val:
            batch = put(batch)
            val_loss += float(
                eval_step(
                    trainable, state.frozen,
                    batch["source_image"], batch["target_image"],
                )
            )
            n_val += 1
        val_loss /= max(n_val, 1)
        dt = time.time() - t0
        pairs_per_s = (
            (len(losses) - n_preloaded) * loader.batch_size
            / max(train_dt, 1e-9)
        )
        print(
            f"Epoch {epoch}: train {train_loss:.4f}  val {val_loss:.4f}  "
            f"({dt:.1f}s, train {pairs_per_s:.1f} pairs/s)",
            flush=True,
        )
        obs.gauge("train.pairs_per_s").set(pairs_per_s)
        obs.event("epoch", epoch=epoch, train_loss=train_loss,
                  val_loss=val_loss, pairs_per_s=pairs_per_s, dur_s=dt,
                  n_steps=len(losses) - n_preloaded, n_val=n_val)
        # Metrics snapshots ride the epoch boundary — an existing host
        # sync point (train_loss/val_loss were just fetched).
        obs.get_run().flush_metrics(phase=f"epoch{epoch}")
        train_losses.append(train_loss)
        val_losses.append(val_loss)

        # With an empty val loader the 0.0 fallback must not drive best-
        # checkpoint selection (it would pin "best" to epoch 1 forever);
        # fall back to tracking the train loss instead.
        select_loss = val_loss if n_val else train_loss
        is_best = select_loss < best_val
        best_val = min(select_loss, best_val)
        # Checkpoints are written by the writer only (host 0, or rank 0
        # of the live generation in elastic mode): params/opt state are
        # replicated, so other hosts would race identical writes on shared
        # storage (and per-host strftime run dirs can straddle a minute).
        if writer and (driver is None or driver.n_hosts == 1
                       or driver.commit_barrier(epoch, len(loader))):
            full_params = {
                "backbone": trainable.get("backbone", state.frozen["backbone"]),
                "neigh_consensus": trainable["neigh_consensus"],
            }
            save_checkpoint(
                ckpt_dir, full_params, config, epoch,
                opt_state=opt_state,
                extra={
                    "train_loss": train_losses,
                    "val_loss": val_losses,
                    "best_val_loss": best_val,
                    "args": vars(args),
                },
                is_best=is_best,
            )
            if driver is not None:
                # The epoch COMPLETED: survivors of a later shrink
                # resume at the next epoch's first step.
                driver.note_commit(epoch + 1, 0)
    watch.close()


if __name__ == "__main__":
    main()
