"""Weak-supervision training CLI.

Usage (defaults reproduce the reference's published PF-Pascal run,
train.py:34-49 of the reference tree):

    python -m ncnet_tpu.cli.train --dataset_image_path datasets/pf-pascal \
        --dataset_csv_path datasets/pf-pascal/image_pairs

Data parallelism: the batch is sharded over all available devices on a 'dp'
mesh; the jitted step contains both forward passes and the Adam update, and
XLA inserts the gradient allreduce over ICI.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..data import ImagePairDataset, DataLoader
from ..parallel import make_mesh, multihost
from ..reliability import failpoints
from ..training import (
    create_train_state,
    load_opt_state,
    make_train_step,
    resolve_resume_dir,
    save_checkpoint,
    shard_batch,
    replicate_state,
)
from .common import build_model


def main(argv=None):
    parser = argparse.ArgumentParser(description="NCNet-TPU weak-supervision training")
    parser.add_argument("--checkpoint", type=str, default="")
    parser.add_argument("--image_size", type=int, default=400)
    parser.add_argument("--dataset_image_path", type=str, default="datasets/pf-pascal/")
    parser.add_argument(
        "--dataset_csv_path", type=str, default="datasets/pf-pascal/image_pairs/"
    )
    parser.add_argument("--num_epochs", type=int, default=5)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--ncons_kernel_sizes", nargs="+", type=int, default=[5, 5, 5])
    parser.add_argument("--ncons_channels", nargs="+", type=int, default=[16, 16, 1])
    parser.add_argument("--backbone", type=str, default="resnet101")
    parser.add_argument("--result_model_dir", type=str, default="trained_models")
    parser.add_argument("--result_model_fn", type=str, default="checkpoint_adam")
    parser.add_argument("--fe_finetune_params", type=int, default=0)
    # Recompute backbone activations in the backward pass (HBM lever for
    # fine-tuning at high resolution / large batch).
    parser.add_argument("--remat_backbone", action="store_true", default=False)
    # Gradient accumulation over N sequential micro-batches: only one
    # micro-batch of AD activations is live at a time (lax.scan), the HBM
    # lever for the reference's batch-16 schedule. Negatives roll within
    # each micro-batch (see make_train_step). batch_size must divide by N.
    parser.add_argument("--grad_accum", type=int, default=1)
    parser.add_argument("--num_workers", type=int, default=8)
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--log_interval", type=int, default=1)
    parser.add_argument(
        "--run_log", type=str, default="auto",
        help="structured JSONL run log (docs/OBSERVABILITY.md): 'auto' "
        "writes runlog-train-<stamp>.jsonl into the run's checkpoint "
        "dir (host 0 only), a path writes there, empty disables",
    )
    # Preemption story (SURVEY §5): --save_interval N writes a rolling
    # mid-epoch checkpoint (tag "step") every N steps; --resume continues
    # a --checkpoint run from its recorded (epoch, step) instead of from
    # epoch 1 — the loader's per-epoch shuffle is a pure function of
    # (seed, epoch), so the exact batch schedule replays and the first
    # `step` batches of the resumed epoch are skipped.
    parser.add_argument("--save_interval", type=int, default=0,
                        help="steps between rolling mid-epoch checkpoints "
                        "(0 = per-epoch only)")
    parser.add_argument("--resume", action="store_true", default=False,
                        help="resume epoch/step position from --checkpoint")
    parser.add_argument(
        "--profile_dir", type=str, default="",
        help="capture a jax.profiler trace of the run for TensorBoard/Perfetto",
    )
    # Training observatory (docs/OBSERVABILITY.md "Training
    # observatory"): the divergence sentinel resolves loss/grad-norm a
    # few steps late (never a same-step sync) and applies this policy
    # on NaN/inf or sustained grad-norm drift.
    parser.add_argument(
        "--on_divergence", type=str, default="halt",
        choices=list(obs.train_watch.POLICIES),
        help="divergence policy: halt raises after the train-divergence "
        "flight dump, skip drops the offending steps from the epoch "
        "average and continues, dump-only records and continues",
    )
    parser.add_argument(
        "--step_timeout_s", type=float, default=0.0,
        help="hard per-step watchdog: a device step hung past this many "
        "seconds flight-dumps and exits (0 disables)",
    )
    args = parser.parse_args(argv)

    if args.grad_accum < 1:
        raise SystemExit("--grad_accum must be >= 1")
    if args.grad_accum > 1 and (
        args.batch_size % args.grad_accum
        or args.batch_size // args.grad_accum < 2
    ):
        raise SystemExit(
            f"--grad_accum {args.grad_accum} needs batch_size "
            f"{args.batch_size} divisible by it with a micro-batch >= 2 "
            "(the weak loss rolls negatives within a micro-batch)"
        )

    # --resume must tolerate a preemption INSIDE save_checkpoint's
    # rename-aside swap: the complete checkpoint may sit at the sibling
    # step.tmp / step.old instead of the dir the user named. Resolve
    # before ANY use of args.checkpoint (build_model reads it first).
    if args.resume and args.checkpoint:
        resolved = resolve_resume_dir(args.checkpoint)
        if resolved is None:
            raise SystemExit(
                f"--resume: no complete checkpoint at {args.checkpoint} "
                "(also tried .tmp/.old siblings)"
            )
        if resolved != os.path.normpath(args.checkpoint):
            print(f"resume: swap was interrupted; using {resolved}")
        args.checkpoint = resolved

    # Multi-host bootstrap: a no-op unless a coordinator is configured in
    # the environment (JAX_COORDINATOR_ADDRESS etc., see parallel.multihost).
    # After it, jax.devices() is the GLOBAL device list and the same program
    # runs unchanged on every host.
    multihost.initialize()

    print("NCNet-TPU training")
    print(args)

    config, params = build_model(
        checkpoint=args.checkpoint,
        ncons_kernel_sizes=tuple(args.ncons_kernel_sizes),
        ncons_channels=tuple(args.ncons_channels),
        backbone_cnn=args.backbone,
        seed=args.seed,
    )

    # --fe_finetune_params N fine-tunes the backbone's last N blocks, as in
    # the reference (lib/model.py:75-78 unfreezes the last N parameter
    # groups); N=0 keeps the backbone frozen.
    state, tx = create_train_state(
        params,
        learning_rate=args.lr,
        train_fe=args.fe_finetune_params > 0,
        fe_finetune_blocks=max(args.fe_finetune_params, 1),
    )
    # Resume the optimizer state alongside the params (the reference saves
    # it but never restores it, train.py:203 — a defect not replicated).
    # load_opt_state reads only opt_state.npz (params were already restored
    # by build_model) and raises a clear error on an optimizer mismatch.
    restored_opt = None
    restore_err = None
    if args.checkpoint and os.path.isdir(args.checkpoint):
        try:
            restored_opt = load_opt_state(args.checkpoint, state.opt_state)
        except Exception as exc:  # noqa: BLE001 — re-raised below, after the
            # collective: a host raising here BEFORE the allgather would
            # leave its peers blocked in the collective forever.
            restore_err = exc
        if restored_opt is not None:
            state.opt_state = restored_opt
            print(f"restored optimizer state from {args.checkpoint}")
    # Multi-host: without a shared filesystem, the checkpoint dir (or just
    # opt_state.npz) may exist on only some hosts — host 0 would resume Adam
    # moments while others start fresh, silently diverging the replicated
    # state. Fail loudly on partial restoration instead. The allgather is a
    # collective, so it must run on EVERY host — unconditionally of whether
    # this host found the directory (args.checkpoint itself is identical
    # across hosts: same command line everywhere).
    if args.checkpoint and multihost.process_count() > 1:
        from jax.experimental import multihost_utils

        # -1 = restore raised, 0 = no opt state found, 1 = restored.
        status = -1 if restore_err is not None else int(restored_opt is not None)
        flags = multihost_utils.process_allgather(jnp.int32(status))
        if int(flags.min()) != int(flags.max()):
            raise SystemExit(
                "optimizer-state restore disagrees across hosts "
                f"(per-host status, -1=error 0=absent 1=restored: "
                f"{list(map(int, flags))}); make the checkpoint directory "
                "visible to every host or remove opt_state.npz everywhere"
            ) from restore_err
    if restore_err is not None:
        raise restore_err
    train_step, eval_step = make_train_step(
        config, tx, remat_backbone=args.remat_backbone,
        accum_steps=args.grad_accum,
    )

    # Use the largest device count that divides the MICRO-batch (the unit
    # each scan step of a grad-accumulated run actually shards; requiring
    # only full-batch divisibility would make GSPMD reshard/pad inside
    # every accumulation step). Multi-host requires the full global device
    # count to divide it.
    n_proc = multihost.process_count()
    n_dev = len(jax.devices())
    micro = args.batch_size // max(args.grad_accum, 1)
    if n_proc > 1:
        if micro % n_dev:
            raise SystemExit(
                f"multi-host run: micro-batch {micro} (batch_size "
                f"{args.batch_size} / grad_accum {args.grad_accum}) must "
                f"be divisible by the global device count {n_dev}"
            )
    else:
        while n_dev > 1 and micro % n_dev:
            n_dev -= 1
    mesh = make_mesh((n_dev,), ("dp",)) if n_dev > 1 else None
    if mesh is not None:
        state = replicate_state(state, mesh)
    print(
        f"devices: {len(jax.devices())} (dp axis: {n_dev}, hosts: {n_proc})"
    )

    # Each host decodes only its slice of every (deterministically
    # scheduled) global batch and contributes it to the global array.
    if n_proc > 1:
        batch_slice = multihost.host_local_slice(args.batch_size)
        put = lambda b: multihost.host_local_batch(b, mesh)  # noqa: E731
    else:
        batch_slice = None
        put = lambda b: shard_batch(b, mesh)  # noqa: E731

    size = (args.image_size, args.image_size)
    dataset = ImagePairDataset(
        os.path.join(args.dataset_csv_path, "train_pairs.csv"),
        args.dataset_image_path,
        output_size=size,
        rng=np.random.RandomState(args.seed),
    )
    dataset_val = ImagePairDataset(
        os.path.join(args.dataset_csv_path, "val_pairs.csv"),
        args.dataset_image_path,
        output_size=size,
    )
    if args.batch_size > len(dataset):
        raise SystemExit(
            f"batch_size {args.batch_size} exceeds dataset size {len(dataset)}; "
            "with drop_last this would train on zero batches"
        )
    loader = DataLoader(
        dataset, args.batch_size, shuffle=True, num_workers=args.num_workers,
        seed=args.seed, drop_last=True, batch_slice=batch_slice,
    )
    if args.batch_size > len(dataset_val):
        print(
            f"WARNING: batch_size {args.batch_size} exceeds val-set size "
            f"{len(dataset_val)}; validation will see zero batches, so the "
            "best checkpoint is selected by train loss instead",
            flush=True,
        )
    loader_val = DataLoader(
        dataset_val, args.batch_size, shuffle=False,
        num_workers=args.num_workers, drop_last=True, batch_slice=batch_slice,
    )

    # Claim the run directory ATOMICALLY at launch (exist_ok=False):
    # checkpoints are otherwise written lazily at end of epoch, so two runs
    # started the same minute would silently interleave into one dir.
    # Host 0 claims; other hosts never write (see _epoch_loop).
    suffix = 0
    while True:
        name = time.strftime("%Y-%m-%d_%H%M") + "_" + args.result_model_fn
        if suffix:
            name += f"_{suffix + 1}"
        ckpt_dir = os.path.join(args.result_model_dir, name)
        if multihost.process_index() != 0:
            break
        try:
            os.makedirs(ckpt_dir, exist_ok=False)
            break
        except FileExistsError:
            suffix += 1

    # Telemetry on host 0 only: params/losses are replicated, so one
    # run log per run (same ownership rule as checkpoint writes).
    run_log = None
    if args.run_log and multihost.process_index() == 0:
        run_log = obs.init_run(
            "train",
            args.run_log if args.run_log != "auto"
            else obs.default_log_path(ckpt_dir, "train"),
            args=args,
        )
        run_log.event(
            "devices",
            n_devices=len(jax.devices()),
            platform=jax.devices()[0].platform,
            ckpt_dir=ckpt_dir,
        )

    # --resume: continue from the checkpoint's recorded position. A
    # mid-epoch ("step") checkpoint carries step_in_epoch; a per-epoch one
    # means that epoch COMPLETED, so resumption starts at the next.
    start_epoch, skip_steps, resume_meta = 1, 0, None
    if args.resume:
        if not (args.checkpoint and os.path.isdir(args.checkpoint)):
            raise SystemExit("--resume requires --checkpoint <dir>")
        with open(os.path.join(args.checkpoint, "meta.json")) as f:
            resume_meta = json.load(f)
        if "step_in_epoch" in resume_meta:
            start_epoch = int(resume_meta["epoch"])
            skip_steps = int(resume_meta["step_in_epoch"])
        else:
            start_epoch = int(resume_meta["epoch"]) + 1
        print(f"resuming at epoch {start_epoch}, step {skip_steps}")
        # Multi-host: resolve_resume_dir runs per host against per-host
        # filesystems, so hosts caught at different points of the rolling
        # swap could silently resume from DIFFERENT checkpoints (the
        # opt-state guard above only compares restore status). Compare
        # the resolved position itself and fail loudly on divergence.
        if multihost.process_count() > 1:
            from jax.experimental import multihost_utils

            pos = multihost_utils.process_allgather(
                jnp.array([start_epoch, skip_steps], jnp.int32)
            )
            if not bool((pos == pos[0]).all()):
                raise SystemExit(
                    "resume position disagrees across hosts (per-host "
                    f"[epoch, step]: {pos.tolist()}); the rolling-swap "
                    "siblings resolved differently — make the SAME "
                    "checkpoint state visible to every host"
                )
        # Carry the best/ checkpoint into the new run dir: best_val
        # resumes from meta, so if no post-resume epoch beats it the new
        # run would otherwise end with NO best/ at all (the true best
        # stranded in the abandoned pre-preemption dir).
        if multihost.process_index() == 0:
            # resolve_resume_dir doubles as the completeness check here:
            # best/ uses the same rename-aside swap, so a preemption
            # mid-swap may have left the complete copy at a .tmp/.old
            # sibling — and a partial dir must not be carried.
            best_src = resolve_resume_dir(os.path.join(
                os.path.dirname(os.path.normpath(args.checkpoint)), "best"
            ))
            best_dst = os.path.join(ckpt_dir, "best")
            if best_src and not os.path.exists(best_dst):
                from ..training.checkpoint import copy_checkpoint_dir

                copy_checkpoint_dir(best_src, best_dst)
                print(f"resume: carried best checkpoint from {best_src}")
                # Old-format step metas lack best_val_loss; without a
                # threshold the first post-resume epoch would overwrite
                # the carried best/ unconditionally (inf comparison).
                # Seed it from the carried best's own meta.
                if "best_val_loss" not in resume_meta:
                    try:
                        with open(os.path.join(best_src, "meta.json")) as f:
                            best_meta = json.load(f)
                        seed_val = best_meta.get("best_val_loss")
                        if seed_val is None:
                            # e.g. best/ written by convert_checkpoint
                            # (extra=None): fall back to its loss curve.
                            curve = best_meta.get("val_loss") or []
                            seed_val = min(curve) if curve else None
                        if seed_val is not None:
                            resume_meta["best_val_loss"] = float(seed_val)
                        else:
                            print(
                                "resume: warning: carried best/ records no "
                                "loss; the first post-resume epoch will "
                                "replace it"
                            )
                    except (OSError, ValueError) as exc:
                        print(
                            "resume: warning: could not seed best_val "
                            f"from carried best/ ({exc})"
                        )

    from ..utils.profiling import trace_context

    try:
        with trace_context(args.profile_dir):
            _epoch_loop(args, config, state, train_step, eval_step, loader,
                        loader_val, put, ckpt_dir, start_epoch=start_epoch,
                        skip_steps=skip_steps, resume_meta=resume_meta)
    except BaseException as exc:
        if run_log is not None:
            run_log.close(f"error:{type(exc).__name__}")
        raise
    if run_log is not None:
        run_log.close("ok")
    print("Done!")


def _epoch_loop(args, config, state, train_step, eval_step, loader, loader_val,
                put_batch, ckpt_dir, start_epoch: int = 1,
                skip_steps: int = 0, resume_meta=None):
    from ..data.loader import device_prefetch

    # Restore the loss history and best-checkpoint threshold from the
    # resumed checkpoint's meta so a resume does not silently reset them
    # (a fresh best_val=inf would let the first post-resume epoch steal
    # "best" regardless of the pre-preemption record).
    best_val = float("inf")
    train_losses, val_losses = [], []
    resumed_epoch_losses = []
    if resume_meta is not None:
        train_losses = [float(x) for x in resume_meta.get("train_loss", [])]
        val_losses = [float(x) for x in resume_meta.get("val_loss", [])]
        best_val = float(resume_meta.get("best_val_loss", float("inf")))
        # Per-step losses of the partially-trained epoch: the resumed
        # epoch's train_loss must average ALL its batches, not just the
        # post-resume ones, and an exactly-at-the-boundary checkpoint
        # (step_in_epoch == len(loader)) must still run validation and
        # the per-epoch save for that epoch instead of recording 0.0.
        resumed_epoch_losses = [
            float(x) for x in resume_meta.get("epoch_losses", [])
        ]
    if skip_steps >= len(loader) and not resumed_epoch_losses:
        # Old-format step checkpoint (no epoch_losses) at the exact
        # boundary: the epoch is complete but its per-step losses are
        # gone — skip into the next epoch rather than recording a
        # zero-batch epoch whose 0.0 train_loss would drive
        # best-checkpoint selection.
        start_epoch += 1
        skip_steps = 0
        if start_epoch > args.num_epochs:
            print(
                f"resume: checkpoint already covers all {args.num_epochs} "
                "epochs (its per-step losses predate the epoch_losses "
                "format, so the final epoch's validation cannot be "
                "reconstructed); nothing to train"
            )
    trainable, opt_state = state.trainable, state.opt_state
    # Fast-forward the loader's epoch counter so epoch E shuffles with
    # RandomState(seed + E - 1) exactly as the original run did.
    loader.set_epoch(start_epoch - 1)

    def put(batch):
        out = put_batch(
            {k: batch[k] for k in ("source_image", "target_image")}
        )
        # Manifest ids stay HOST-side (never device-put): the
        # divergence sentinel's ring names offending batches by them.
        if "_indices" in batch:
            out["_indices"] = np.asarray(batch["_indices"])
        return out

    # Training observatory: per-step telemetry + span trees, the
    # bounded-lag divergence sentinel, per-host step beacons, and the
    # optional per-step watchdog (obs/train_watch.py). Dumps land next
    # to the run log when one is active.
    run_path = getattr(obs.get_run(), "path", None)
    watch = obs.train_watch.TrainWatch(
        policy=args.on_divergence,
        lr=args.lr,
        log_interval=args.log_interval,
        host=multihost.host_label(),
        step_timeout_s=args.step_timeout_s,
        flight_dir=os.path.dirname(os.path.abspath(run_path))
        if run_path else None,
    )

    for epoch in range(start_epoch, args.num_epochs + 1):
        t0 = time.time()
        # The resumed epoch starts with the losses of its already-trained
        # batches so train_loss averages the WHOLE epoch.
        losses = list(resumed_epoch_losses) if epoch == start_epoch else []
        n_preloaded = len(losses)
        # Resumed epoch: replay the deterministic schedule; the
        # generator drops already-trained batches before the device
        # transfer (the loader still decodes them, backpressured by
        # its prefetch queue — minutes at worst for a full epoch).
        skip = skip_steps if epoch == start_epoch else 0

        def resumed(it=loader, skip=skip, epoch=epoch):
            if skip >= len(it):
                # Exact-boundary resume: every batch is already trained.
                # Don't decode the whole epoch just to drop it — position
                # the shuffle schedule where a real iteration of epoch
                # `epoch` would have left it (the NEXT iteration shuffles
                # with seed + epoch) and go straight to validation.
                it.set_epoch(epoch)
                return
            for j, b in enumerate(it):
                if j >= skip:
                    yield b

        # One batch in flight: H2D transfer of batch i+1 overlaps step i.
        # Losses stay DEVICE scalars inside the loop — float() would force a
        # full sync every step, serializing dispatch; on a tunneled backend
        # that costs a round trip per batch. The sync happens only at log
        # points (per batch at the default --log_interval 1, matching the
        # reference's per-batch print; raise it to unlock async dispatch)
        # and in the sentinel, which resolves values a few steps old.
        watch.reset_epoch()
        for i, batch in watch.steps(
            device_prefetch(resumed(), put), start=skip
        ):
            # Chaos plant (docs/RELIABILITY.md): error/delay fire here,
            # pre-dispatch; the corrupt mode is consumed downstream by
            # the sentinel's loss resolve in obs/train_watch.py.
            failpoints.fire("train.step", payload=i)
            trainable, opt_state, loss, aux = train_step(
                trainable, state.frozen, opt_state,
                batch["source_image"], batch["target_image"],
            )
            # Books step-time/data-wait histograms, the train.step span
            # tree, the step beacon, and queues loss/grad-norm for the
            # bounded-lag divergence check (may raise TrainDivergence
            # under --on_divergence halt).
            watch.book(
                epoch=epoch, step=i, loss=loss,
                grad_norm=aux["grad_norm"],
                update_ratio=aux["update_ratio"],
                batch_ids=batch.get("_indices"),
            )
            if i % args.log_interval == 0:
                loss = float(loss)  # the only fetch of this scalar
                print(
                    f"Train epoch {epoch} [{i}/{len(loader)}]\tloss: "
                    f"{loss:.6f}",
                    flush=True,
                )
            losses.append(loss)
            if (
                args.save_interval
                and (i + 1) % args.save_interval == 0
                and multihost.process_index() == 0
            ):
                # Fetch each device scalar at most once across all saves
                # (with --log_interval > 1 most entries are still device
                # scalars; re-converting the whole list every save would
                # be O(steps^2 / save_interval) tunnel round trips).
                losses[:] = [
                    l if isinstance(l, float) else float(l) for l in losses
                ]
                full_params = {
                    "backbone": trainable.get(
                        "backbone", state.frozen["backbone"]
                    ),
                    "neigh_consensus": trainable["neigh_consensus"],
                }
                save_checkpoint(
                    ckpt_dir, full_params, config, epoch,
                    opt_state=opt_state,
                    # Completed-epoch history + this epoch's per-step
                    # losses ride along so a resume restores best_val,
                    # the loss curves, AND can finish this epoch with a
                    # correctly-averaged train_loss (ADVICE r3).
                    # best_val is inf until a validation has run; omit
                    # it then (json would emit non-RFC 'Infinity') —
                    # the resume path already .get()s with an inf
                    # default.
                    extra={"step_in_epoch": i + 1, "args": vars(args),
                           "train_loss": train_losses,
                           "val_loss": val_losses,
                           **({"best_val_loss": best_val}
                              if best_val != float("inf") else {}),
                           "epoch_losses": losses},
                    tag="step",
                )
        # Resolve the sentinel's tail before averaging: the last `lag`
        # steps' losses must still pass the divergence check.
        watch.drain()
        loss_vals = [float(l) for l in losses]
        if watch.policy == "skip":
            # skip policy: divergent steps are dropped from the curve
            # (a NaN would otherwise poison the epoch mean and every
            # downstream best-checkpoint comparison) — the run records
            # the skip and keeps training.
            n_bad = sum(1 for v in loss_vals if not math.isfinite(v))
            if n_bad:
                obs.event("train_divergence_skipped", epoch=epoch,
                          n_skipped=n_bad)
                loss_vals = [v for v in loss_vals if math.isfinite(v)]
        train_loss = float(np.mean(loss_vals)) if loss_vals else 0.0
        train_dt = time.time() - t0

        val_loss, n_val = 0.0, 0
        for batch in loader_val:
            batch = put(batch)
            val_loss += float(
                eval_step(
                    trainable, state.frozen,
                    batch["source_image"], batch["target_image"],
                )
            )
            n_val += 1
        val_loss /= max(n_val, 1)
        dt = time.time() - t0
        pairs_per_s = (
            (len(losses) - n_preloaded) * args.batch_size
            / max(train_dt, 1e-9)
        )
        print(
            f"Epoch {epoch}: train {train_loss:.4f}  val {val_loss:.4f}  "
            f"({dt:.1f}s, train {pairs_per_s:.1f} pairs/s)",
            flush=True,
        )
        obs.gauge("train.pairs_per_s").set(pairs_per_s)
        obs.event("epoch", epoch=epoch, train_loss=train_loss,
                  val_loss=val_loss, pairs_per_s=pairs_per_s, dur_s=dt,
                  n_steps=len(losses) - n_preloaded, n_val=n_val)
        # Metrics snapshots ride the epoch boundary — an existing host
        # sync point (train_loss/val_loss were just fetched).
        obs.get_run().flush_metrics(phase=f"epoch{epoch}")
        train_losses.append(train_loss)
        val_losses.append(val_loss)

        # With an empty val loader the 0.0 fallback must not drive best-
        # checkpoint selection (it would pin "best" to epoch 1 forever);
        # fall back to tracking the train loss instead.
        select_loss = val_loss if n_val else train_loss
        is_best = select_loss < best_val
        best_val = min(select_loss, best_val)
        # Checkpoints are written by host 0 only: params/opt state are
        # replicated, so other hosts would race identical writes on shared
        # storage (and per-host strftime run dirs can straddle a minute).
        if multihost.process_index() == 0:
            full_params = {
                "backbone": trainable.get("backbone", state.frozen["backbone"]),
                "neigh_consensus": trainable["neigh_consensus"],
            }
            save_checkpoint(
                ckpt_dir, full_params, config, epoch,
                opt_state=opt_state,
                extra={
                    "train_loss": train_losses,
                    "val_loss": val_losses,
                    "best_val_loss": best_val,
                    "args": vars(args),
                },
                is_best=is_best,
            )
    watch.close()


if __name__ == "__main__":
    main()
