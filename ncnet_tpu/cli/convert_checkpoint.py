"""Convert a reference PyTorch checkpoint (.pth.tar) to a native checkpoint.

The published NCNet checkpoints (trained_models/download.sh: ncnet_pfpascal,
ncnet_ivd) restore directly into every CLI via --checkpoint <file>.pth.tar;
this tool materializes the conversion once into the native self-describing
format (training/checkpoint.py) so later runs skip the torch dependency and
the on-the-fly key remapping.

Usage:
    ncnet-convert-checkpoint trained_models/ncnet_pfpascal.pth.tar \
        trained_models/ncnet_pfpascal_native
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("src", help="reference .pth.tar checkpoint")
    p.add_argument("dst", help="output native checkpoint directory")
    p.add_argument(
        "--verify", action="store_true", default=True,
        help="round-trip restore and compare a forward pass (default on)",
    )
    p.add_argument("--no-verify", dest="verify", action="store_false")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from ncnet_tpu.models import NCNetConfig
    from ncnet_tpu.models.convert import load_reference_checkpoint
    from ncnet_tpu.training.checkpoint import load_checkpoint, save_checkpoint

    params, arch = load_reference_checkpoint(args.src)
    config = NCNetConfig(
        backbone=arch["backbone"],
        ncons_kernel_sizes=arch["ncons_kernel_sizes"],
        ncons_channels=arch["ncons_channels"],
    )
    n_leaves = len(jax.tree.leaves(params))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    print(f"loaded {args.src}: {arch['backbone'].cnn}, "
          f"ncons {arch['ncons_kernel_sizes']}/{arch['ncons_channels']}, "
          f"{n_leaves} tensors / {n_params / 1e6:.1f}M params")

    save_checkpoint(args.dst, params, config, epoch=0, is_best=True)
    tag = os.path.join(args.dst, "best")
    print(f"wrote {tag}")

    if args.verify:
        restored = load_checkpoint(tag)
        try:
            # tree.map raises on structure mismatch (dropped/extra tensors).
            equal = jax.tree.map(
                lambda a, b: bool(np.array_equal(np.asarray(a), np.asarray(b))),
                params,
                restored["params"],
            )
            ok = all(jax.tree.leaves(equal))
        except ValueError:
            ok = False
        if not ok or restored["config"] != config:
            print("VERIFY FAILED: round-trip mismatch", file=sys.stderr)
            sys.exit(1)
        print("verify: round-trip exact")


if __name__ == "__main__":
    main()
