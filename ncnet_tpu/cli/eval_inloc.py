"""InLoc dense-matching CLI (parity: eval_inloc.py of the reference).

Per query x top-N shortlisted panos: run the high-resolution matching model
(relocalization maxpool k=2, bf16 correlation) and write
`matches/<experiment>/<q>.mat` files consumed unchanged by the Matlab
P3P-RANSAC localization stage (compute_densePE_NCNet.m).

TPU-first differences from the reference:
  * images are resized so feature dims are divisible by k_size AND the
    aspect is snapped to a small bucket set — every distinct shape is one
    XLA compilation, so bucketing bounds recompiles (SURVEY.md §7 item 7);
  * the 4-D pipeline runs in bf16-correlation + f32 accumulation instead of
    fp16 storage;
  * with --spatial_shards > 1 the correlation tensor is spatially sharded across
    the device mesh (parallel/corr_sharding.py) — the memory that forces the
    reference to fp16 + pool is instead split over chips;
  * finished queries are skipped by output-file existence, keeping the
    reference's idempotent-resume pattern (SURVEY.md §5).
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from .. import obs
from ..evals import (
    dedup_matches,
    fill_matches,
    inloc_device_matches,
    matches_buffer,
    write_matches_mat,
)
from ..models.ncnet import (
    extract_features,
    ncnet_forward_from_features,
)
# The same-shape bucket accumulator both batched drivers ride lives in
# utils/batching (promoted there so the online serving micro-batcher
# shares the exact grouping heuristics); the historical `_MissGroups`
# name keeps this module's driver code readable.
from ..utils.batching import ShapeBuckets as _MissGroups
from .common import build_model


def _ragged_miss_stacks() -> bool:
    """NCNET_RAGGED_MISS_STACKS (trace time, default 1): dispatch
    partial miss stacks at their TRUE size instead of padding to
    --pano_batch.

    Padding repeats the last pano, so a drain-time group of 1 pays the
    full p-stack program — p backbones AND p consensus/extract scans —
    for one useful pano (`_MissGroups.pad`). At the replayed InLoc
    steady state (tools/cache_steady_state.py, 53% hit-rate) 38% of
    queries drain a partial group, so the waste is first-order: the
    measured cached steady state under padding was 9.59 pairs/s/chip —
    BELOW the 9.74 cold path, because mixed queries paid their hits
    plus fully-padded miss stacks. Ragged dispatch lets the jitted
    batch program retrace at each distinct stack size m < p: one extra
    compile per size, ONE-TIME (persistent compile cache), after which
    every partial group costs only its true size. PROMOTED to default
    2026-08-02 on the v5e measurement: steady state 10.75 vs 9.59
    pairs/s/chip (+12%; tools/bench_steady_state_hw.py, both logs in
    docs/tpu_r05/). Padding stays available (=0) for environments
    where per-shape compiles are expensive and uncached (cold CI)."""
    return os.environ.get("NCNET_RAGGED_MISS_STACKS", "1") == "1"


def _bb_group_size(n: int, bb: int) -> int:
    """Largest divisor of stack size ``n`` that is <= ``bb`` (min 1).

    The ONE definition of the pano-backbone grouping: the batch programs
    use it to shape their ``lax.map`` groups and the feature cache's
    producer key uses it to name the program that computed an entry —
    these must agree or a disk entry produced by one grouping would hit
    under another's key.
    """
    nb = max(1, min(bb, n))
    while n % nb:
        nb -= 1
    return nb


def inloc_resize_shape(h, w, image_size, k_size, scale_factor=0.0625,
                       h_unit=0, w_unit=0):
    """Target (h, w): long side ~image_size, feature dims divisible by the
    per-axis alignment units (default k_size; the sharded forward passes
    h_unit=shards*k_size; the vector-padding bucketing passes 16 on both —
    see resolve_feat_units).

    Mirrors the reference's alignment arithmetic (eval_inloc.py:84-89):
    floor(dim / (long/image_size) * scale/unit) / scale * unit.
    """
    h_unit = h_unit or k_size
    w_unit = w_unit or k_size
    ratio = max(h, w) / image_size
    out_h = int(np.floor(h / ratio * scale_factor / h_unit) / scale_factor * h_unit)
    out_w = int(np.floor(w / ratio * scale_factor / w_unit) / scale_factor * w_unit)
    # Small inputs (or large units) can floor a dim to ZERO feature cells —
    # downstream that is a 0-sized correlation axis (opaque Pallas grid
    # crash). Clamp to one alignment unit: slight upscale beats a crash.
    out_h = max(out_h, int(h_unit / scale_factor))
    out_w = max(out_w, int(w_unit / scale_factor))
    return out_h, out_w


def resolve_feat_units(feat_unit, image_size, k_size, extra_align: int = 1):
    """(h_unit, w_unit) in feature cells for inloc_resize_shape.

    feat_unit < 0 is 'auto': 16 at InLoc scale (image_size >= 1024), else
    plain k_size alignment. 16 feature cells make the POOLED dims
    multiples of 8 — the 2026-07-31 v5e session measured the consensus
    stage 34% slower at the unaligned 100x75 pooled shape than at 100x72
    (vector padding, docs/tpu_r02/session_0610.log), and the snap also
    trims ~8% raw work (3200x2400 px -> 3072x2304, features 192x144).
    The same class of resolution approximation as the reference's own
    k-size alignment (eval_inloc.py:84-89); pass --feat_unit 2 (= k_size)
    to reproduce the reference's exact dims.

    Units are lcm'd with the mandatory divisors (k_size; height also
    shards*k_size) so sharding constraints always win — but when the lcm
    would blow past 2x the requested unit (non-power-of-two shard counts:
    lcm(16, 10) = 80 cells is a silent 20%+ resolution loss), the vector
    alignment is dropped for that axis and only the mandatory divisor
    remains.
    """
    if feat_unit is None or feat_unit < 0:
        feat_unit = 16 if image_size >= 1024 else k_size
    feat_unit = max(int(feat_unit), 1)

    def unit_for(mandatory):
        u = int(np.lcm(feat_unit, mandatory))
        return u if u <= 2 * feat_unit else mandatory

    return unit_for(k_size * max(extra_align, 1)), unit_for(k_size)


def load_inloc_image(path, image_size, k_size, extra_align: int = 1,
                     feat_unit: int = -1):
    """extra_align multiplies the HEIGHT divisibility unit — the spatially-
    sharded forward needs iA (and, via the transposed pass, iB) divisible by
    (shards * k_size). feat_unit: see resolve_feat_units (-1 = auto)."""
    from PIL import Image

    from ..data.image_io import load_and_resize_chw

    with Image.open(path) as im:  # header-only: dims without a full decode
        w, h = im.size
    h_unit, w_unit = resolve_feat_units(
        feat_unit, image_size, k_size, extra_align
    )
    oh, ow = inloc_resize_shape(
        h, w, image_size, k_size, h_unit=h_unit, w_unit=w_unit
    )
    chw, _ = load_and_resize_chw(path, oh, ow, normalize=True)
    return chw[None]


def main(argv=None):
    parser = argparse.ArgumentParser(description="NCNet-TPU InLoc matching")
    parser.add_argument("--checkpoint", type=str, default="")
    parser.add_argument(
        "--inloc_shortlist",
        type=str,
        default="datasets/inloc/densePE_top100_shortlist_cvpr18.mat",
    )
    parser.add_argument("--k_size", type=int, default=2)
    parser.add_argument("--image_size", type=int, default=3200)
    parser.add_argument("--n_queries", type=int, default=356)
    parser.add_argument("--n_panos", type=int, default=10)
    parser.add_argument("--softmax", action="store_true", default=True)
    parser.add_argument("--no-softmax", dest="softmax", action="store_false")
    parser.add_argument(
        "--matching_both_directions", action="store_true", default=True
    )
    parser.add_argument(
        "--flip_matching_direction", action="store_true", default=False
    )
    parser.add_argument("--pano_path", type=str, default="datasets/inloc/pano/")
    parser.add_argument(
        "--query_path", type=str, default="datasets/inloc/query/iphone7/"
    )
    parser.add_argument("--output_dir", type=str, default="matches")
    parser.add_argument("--resume", action="store_true", default=True)
    # TPU fast path: bf16 conv compute in the backbone (2x MXU, half the
    # activation HBM). The workload is half-precision end-to-end anyway
    # (parity: eval_inloc.py:50 runs the reference in fp16).
    parser.add_argument("--backbone_bf16", action="store_true", default=True)
    parser.add_argument(
        "--no-backbone_bf16", dest="backbone_bf16", action="store_false"
    )
    # Multi-chip: shard the correlation tensor along iA over N devices
    # (parallel/inloc_sharded.py). 1 = single-device.
    parser.add_argument("--spatial_shards", type=int, default=1)
    parser.add_argument(
        "--profile_dir", type=str, default="",
        help="capture a jax.profiler trace of the run for TensorBoard/Perfetto",
    )
    parser.add_argument(
        "--pano_batch", type=int, default=1,
        help="panos per device program: same-bucket panos are stacked and "
        "scanned inside ONE dispatch (ragged groups padded by repetition). "
        "Per-dispatch latency dominates tunneled backends (~50 ms each, "
        "2026-07-31 measurement); 1 = one dispatch per pano.",
    )
    # Multi-chip pano fan-out: each device of a dp mesh runs the COMPLETE
    # batch-1 per-pano program (forward + Pallas extraction) on a
    # different shortlist pano via shard_map — no halo exchange, no
    # sharded-op constraints, near-linear scaling for the headline
    # workload. Complementary to --spatial_shards (which splits ONE pair
    # when a single chip's HBM can't hold it).
    parser.add_argument(
        "--pano_dp", type=int, default=0,
        help="fan panos over an N-device data-parallel mesh, one pano per "
        "chip per dispatch (0 = off, -1 = all visible devices); uses the "
        "--pano_batch stacking machinery with group size N",
    )
    # Cross-query pano-feature cache (VERDICT r3 item 2): the shortlists
    # repeat panos across the 356 queries but the reference recomputes
    # every pano's backbone per pair (eval_inloc.py:124-137); a hit skips
    # the pano backbone (~87 ms of ~300 per pano on v5e) AND the 3200 px
    # host decode entirely. Host-memory LRU bounded in MB (features are
    # ~57 MB bf16 per pano at the default bucket -> 4 GiB holds ~75);
    # optional disk tier for re-runs. Bit-parity: a hit replays the
    # identical feature tensor through the identical match program.
    parser.add_argument(
        "--pano_feature_cache_mb", type=int, default=4096,
        help="host-memory budget for the cross-query pano feature cache "
        "(0 disables; composes with --pano_batch, disabled under "
        "--spatial_shards/--pano_dp)",
    )
    parser.add_argument(
        "--pano_feature_cache_dir", type=str, default="",
        help="optional disk tier for the pano feature cache (entries "
        "persist across runs, keyed by checkpoint + resize bucket)",
    )
    parser.add_argument(
        "--run_log", type=str, default="auto",
        help="structured JSONL run log (docs/OBSERVABILITY.md): 'auto' "
        "writes runlog-eval_inloc-<stamp>.jsonl into the experiment "
        "output dir, a path writes there, empty disables",
    )
    parser.add_argument(
        "--feat_unit", type=int, default=-1,
        help="feature-dim alignment unit for the resize buckets (-1 auto: "
        "16 at InLoc scale so pooled dims are vector-friendly multiples "
        "of 8, else k_size; pass 2 for the reference's exact dims) — see "
        "resolve_feat_units",
    )
    args = parser.parse_args(argv)
    if args.spatial_shards < 1:
        parser.error("--spatial_shards must be >= 1")
    if args.pano_batch < 1:
        parser.error("--pano_batch must be >= 1")
    if args.pano_batch > 1 and args.spatial_shards > 1:
        parser.error("--pano_batch requires --spatial_shards 1 (the sharded "
                     "pipeline batches across the mesh instead)")
    if args.pano_dp and (args.spatial_shards > 1 or args.pano_batch > 1):
        parser.error("--pano_dp replaces --pano_batch grouping and requires "
                     "--spatial_shards 1")
    if args.pano_dp:
        # Any negative value means "all visible devices". Ride the
        # --pano_batch grouping machinery: same-bucket stacks of exactly
        # one pano per device.
        n_vis = len(jax.devices())
        args.pano_batch = n_vis if args.pano_dp < 0 else args.pano_dp
        if args.pano_batch > n_vis:
            parser.error(
                f"--pano_dp {args.pano_dp} exceeds the {n_vis} visible "
                "devices"
            )

    from scipy.io import loadmat

    config, params = build_model(
        checkpoint=args.checkpoint,
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        relocalization_k_size=args.k_size,
        half_precision=True,
        backbone_bf16=args.backbone_bf16,
    )

    experiment = (
        os.path.basename(args.inloc_shortlist).split(".")[0]
        + f"_SZ_{args.image_size}_K_{args.k_size}"
        + ("_BOTHDIRS" if args.matching_both_directions else "")
        + ("_SOFTMAX" if args.softmax else "")
    )
    if args.checkpoint:
        # Key outputs by checkpoint so --resume never reuses another
        # checkpoint's matches (parity: eval_inloc.py:69-71). Generic
        # leaf names (every converted reference checkpoint ends in
        # .../best) take the parent dir into the key, else two different
        # conversions collide on CHECKPOINT_best and --resume silently
        # scores the other model's matches.
        parts = os.path.normpath(args.checkpoint).split(os.sep)
        ckpt_name = parts[-1].split(".")[0]
        if ckpt_name in ("best", "latest", "step") and len(parts) > 1:
            ckpt_name = f"{parts[-2].split('.')[0]}_{ckpt_name}"
        experiment += f"_CHECKPOINT_{ckpt_name}"
    out_dir = os.path.join(args.output_dir, experiment)
    os.makedirs(out_dir, exist_ok=True)
    print(f"Output matches folder: {out_dir}")

    run_log = None
    if args.run_log:
        # Default inside the experiment dir: one experiment, one place
        # for its artifacts. The Matlab stage reads <q>.mat paths, so a
        # runlog-*.jsonl alongside them is inert.
        run_log = obs.init_run(
            "eval_inloc",
            args.run_log if args.run_log != "auto"
            else obs.default_log_path(out_dir, "eval_inloc"),
            args=args,
        )
        # Backend already dialed (build_model jitted above), so the
        # device list is free to record here — run_start deliberately
        # doesn't (obs.events._device_metadata).
        run_log.event(
            "devices",
            n_devices=len(jax.devices()),
            platform=jax.devices()[0].platform,
        )

    # State the resolved geometry up front (ADVICE r2): the default
    # feat_unit=16 buckets 3200x2400 px panos to 3072x2304 (features
    # 192x144), which is NOT the reference's exact 200x150 feature grid —
    # results are comparable to the reference pipeline only with
    # --feat_unit 2. Printing the units here makes the choice auditable
    # in every eval log.
    units = resolve_feat_units(args.feat_unit, args.image_size, args.k_size,
                               extra_align=args.spatial_shards)
    example_h, example_w = inloc_resize_shape(
        args.image_size, args.image_size * 3 // 4, args.image_size,
        args.k_size, h_unit=units[0], w_unit=units[1],
    )
    print(
        f"Resize buckets: feat units {units} (--feat_unit {args.feat_unit}; "
        f"e.g. a {args.image_size}x{args.image_size * 3 // 4} pano -> "
        f"{example_h}x{example_w} px, features ~{example_h // 16}x"
        f"{example_w // 16}). Pass --feat_unit 2 to reproduce the "
        "reference's exact feature dims."
    )
    obs.event("config", experiment=experiment, out_dir=out_dir,
              feat_units=list(units))

    # Consult the persistent consensus strategy cache (ops/autotune.py)
    # for the representative shape bucket, and say up front whether this
    # eval runs a tuned plan or the static heuristic — the same consult
    # neigh_consensus_apply makes at trace time, surfaced before the
    # first multi-minute compile instead of buried inside it.
    from ..ops import autotune as _autotune

    k = max(args.k_size, 1)
    fh, fw = example_h // 16 // k, example_w // 16 // k
    example_corr = (1, 1, fh, fw, fh, fw)
    tuned = _autotune.lookup_plan(
        example_corr, config.corr_dtype, params["neigh_consensus"],
        symmetric=config.symmetric_mode, full=True,
    )
    obs.event("autotune", action="consult", where="eval_inloc",
              corr_shape=list(example_corr),
              cache_hit=tuned is not None,
              ms=tuned.get("ms") if tuned else None,
              plan=tuned.get("plan") if tuned else None,
              cache_path=_autotune.cache_path())

    dbmat = loadmat(args.inloc_shortlist)
    db = dbmat["ImgList"][0, :]
    pano_fn_all = np.vstack([db[q][1] for q in range(len(db))])

    # Per-pano device program. The query's backbone features are computed
    # once per query (the reference recomputes them for every one of the 10
    # panos, eval_inloc.py:137) and the pano forward + both-direction match
    # extraction compile into ONE executable — a tunneled backend pays
    # milliseconds of latency per dispatch, so op-by-op extraction is the
    # difference between one round-trip and dozens. One jit per distinct
    # (src, tgt) shape pair; the bucketed resize keeps this cache small.
    match_kwargs = dict(
        k_size=args.k_size,
        do_softmax=args.softmax,
        both_directions=args.matching_both_directions,
        invert_direction=args.flip_matching_direction,
    )
    if args.spatial_shards > 1:
        from ..parallel import make_mesh, make_sharded_inloc_parts

        mesh = make_mesh((args.spatial_shards,), ("sp",))
        query_features, sharded_from_features = make_sharded_inloc_parts(
            config, mesh
        )

        @jax.jit
        def pano_matches(params, feat_a, tgt):
            corr, delta = sharded_from_features(params, feat_a, tgt)
            # Pin the XLA extraction: its reductions partition along the
            # sharded corr axes under GSPMD, whereas the Pallas statistics
            # kernel has no partitioning rule and would force a full
            # per-device replication of the corr tensor.
            return inloc_device_matches(
                corr, delta4d=delta, impl="xla", **match_kwargs
            )
    else:

        @jax.jit
        def query_features(params, src):
            return extract_features(config, params, src)

        # ONE forward+match composition shared by all three programs below
        # — the hit/miss bit-parity contract of the feature cache depends
        # on them staying the same math.
        def _match_from_feats(params, feat_a, feat_b):
            corr, delta = ncnet_forward_from_features(
                config, params, feat_a, feat_b
            )
            return inloc_device_matches(corr, delta4d=delta, **match_kwargs)

        def pano_matches_one(params, feat_a, tgt):
            feat_b = extract_features(config, params, tgt)
            return _match_from_feats(params, feat_a, feat_b)

        pano_matches = jax.jit(pano_matches_one)

        # Cache paths: the miss program additionally RETURNS the pano
        # features (same math — extract_features output is what the fused
        # program consumes internally, so hit and miss produce identical
        # matches); the hit program consumes host-cached features.
        # Features are cached in bf16: the correlation kernels cast
        # features to bf16 as their first op (ops/pallas_kernels.py:374,
        # ops/correlation.py:33), so the hit path stays bit-identical
        # while the entry — and its D2H on store / H2D on hit — is half
        # the bytes (~57 MB/pano instead of 113), doubling the panos a
        # given --pano_feature_cache_mb budget holds.
        @jax.jit
        def pano_matches_with_feats(params, feat_a, tgt):
            feat_b = extract_features(config, params, tgt)
            return (_match_from_feats(params, feat_a, feat_b),
                    feat_b.astype(jnp.bfloat16))

        match_from_cached_feats = jax.jit(_match_from_feats)

        if args.pano_dp:
            # One COMPLETE batch-1 per-pano program per device: shard_map
            # hands each device its [1, 3, H, W] shard, so the unmodified
            # single-pano math (incl. the batch-1 Pallas extraction) runs
            # per chip with zero cross-device traffic; outputs restack to
            # [n_dp, n_matches] exactly like the scan path's.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel import make_mesh
            from ..parallel.mesh import shard_map_compat

            dp_mesh = make_mesh((args.pano_batch,), ("dp",))
            stack_sharding = NamedSharding(dp_mesh, P("dp"))

            def _one_shard(params, feat_a, tgt):
                m = pano_matches_one(params, feat_a, tgt)
                return tuple(v[None] for v in m)

            _pano_dp_jit = jax.jit(shard_map_compat(
                _one_shard,
                mesh=dp_mesh,
                in_specs=(P(), P(), P("dp")),
                out_specs=P("dp"),
            ))

            # Replicate the weights over the mesh ONCE — otherwise every
            # dispatch re-broadcasts the backbone from device 0.
            rep = NamedSharding(dp_mesh, P())
            params_rep = jax.device_put(params, rep)

            def pano_matches_dp(_params, feat_a, stack):
                return _pano_dp_jit(
                    params_rep, jax.device_put(feat_a, rep), stack
                )

            def dp_stack(imgs):
                # Host stack -> per-device H2D placement directly (no
                # chip-0 staging of the full [n_dp, 3, H, W] stack;
                # load_pano keeps dp panos on the host).
                return jax.device_put(
                    np.concatenate(imgs, axis=0), stack_sharding
                )

        # Pano-backbone batching (NCNET_PANO_BACKBONE_BATCH=n, trace
        # time): batch the group's backbones before the per-pano scan.
        # Batch-1 backbone convs run at 12-16% MXU utilization (round-2
        # trace); batching feeds the MXU while the scan keeps the
        # HBM-bound corr/consensus tensors at batch-1 size. bench.py
        # carries the same knob.
        # Default 5 (promoted 2026-08-01, session_1128 bench matrix:
        # 9.69 vs 6.09 pairs/s; bb10 and bb5+conv1fold both lose).
        bb = int(os.environ.get("NCNET_PANO_BACKBONE_BATCH", "5") or 5)

        def _batched_feats(params, tgt_stack):
            # The bb-grouped backbone both batch programs share — ONE
            # definition, because the cache's producer key promises the
            # miss program uses exactly _bb_group_size's grouping.
            n = tgt_stack.shape[0]
            nb = _bb_group_size(n, bb)
            groups = tgt_stack.reshape(n // nb, nb, *tgt_stack.shape[1:])
            feats_b = jax.lax.map(
                lambda g: extract_features(config, params, g), groups
            )
            return feats_b.reshape(n, 1, *feats_b.shape[2:])

        @jax.jit
        def pano_matches_batch(params, feat_a, tgt_stack):
            # lax.scan over a same-shape pano stack: the whole group is one
            # dispatch; outputs stack to [P, n] per match array.
            if bb > 1:
                feats_b = _batched_feats(params, tgt_stack)

                def body_f(_, feat_b):
                    corr, delta = ncnet_forward_from_features(
                        config, params, feat_a, feat_b
                    )
                    return None, inloc_device_matches(
                        corr, delta4d=delta, **match_kwargs
                    )

                _, ms = jax.lax.scan(body_f, None, feats_b)
                return ms

            def body(_, tgt):
                return None, pano_matches_one(params, feat_a, tgt[None])

            _, ms = jax.lax.scan(body, None, tgt_stack)
            return ms

        # Cached-batched miss program: same-shape stack of cache MISSES
        # -> batched backbone (the promoted bb grouping) + per-pano match
        # scan, additionally returning the stack's features (bf16, what
        # the cache stores) so a cached run keeps the batched-backbone
        # miss cost instead of falling back to per-pano backbones.
        @jax.jit
        def pano_matches_batch_with_feats(params, feat_a, tgt_stack):
            # _batched_feats unconditionally (nb=1 when bb<=1): the
            # producer key "bb<nb>" must name ONE program structure.
            feats_b = _batched_feats(params, tgt_stack)

            def body_wf(_, feat_b):
                # Through _match_from_feats: the hit program
                # (match_from_cached_feats) is the same composition, so
                # an edit to it cannot desynchronize hits from misses.
                return None, _match_from_feats(params, feat_a, feat_b)

            _, ms = jax.lax.scan(body_wf, None, feats_b)
            return ms, feats_b.astype(jnp.bfloat16)

    n_matches = int(
        (args.image_size * 0.0625 / args.k_size)
        * np.floor((args.image_size * 0.0625 / args.k_size) * 0.75)
    )
    if args.matching_both_directions:
        n_matches *= 2

    cache = None
    if args.pano_feature_cache_mb > 0:
        if args.spatial_shards > 1 or args.pano_dp:
            print("pano-feature cache: disabled (--spatial_shards/"
                  "--pano_dp run their own feature plumbing)")
        else:
            from ..evals.feature_cache import (
                PanoFeatureCache,
                model_cache_key,
            )

            # The key also names the PROGRAM that produced the features:
            # the batched miss program's nb-grouped backbone is a
            # different XLA artifact from the sequential one (bf16
            # rounding differs within ~2e-3 scores), so a disk tier
            # populated by a --pano_batch run must MISS in a sequential
            # run (recompute) rather than silently break the sequential
            # mode's strict hit/miss bit-parity.
            if args.pano_batch > 1:
                # Miss stacks are always padded to exactly --pano_batch,
                # so the traced program is named by BOTH the stack size
                # and its _bb_group_size grouping — two sweep members
                # with the same bb but different --pano_batch compile
                # different XLA artifacts and must not share entries.
                producer = "|p%d-bb%d" % (
                    args.pano_batch,
                    _bb_group_size(args.pano_batch, bb),
                )
                if _ragged_miss_stacks():
                    # Ragged runs mix entries from m-sized programs
                    # (m <= p) — rounding-equivalent under the batched
                    # contract, but a different artifact set from the
                    # always-padded mode, so the two must not share a
                    # disk tier.
                    producer += "-r"
            else:
                # Sequential producer = EMPTY suffix: every disk entry
                # written before producer keying existed was
                # sequential-produced, and the suffix must not
                # invalidate those tiers (or the legacy-f32 migration
                # in feature_cache.get would never fire).
                producer = ""
            cache = PanoFeatureCache(
                args.pano_feature_cache_mb * 1024 * 1024,
                disk_dir=args.pano_feature_cache_dir or None,
                # seed=1: build_model's default init seed (cli/common.py)
                # — the disk-tier key must name the weights that actually
                # produced the features.
                model_key=(
                    model_cache_key(args.checkpoint, seed=1) + producer
                ),
                # Normalizes legacy f32 disk entries to the bf16 the miss
                # program now stores (one entry size, one hit-program
                # dtype specialization).
                store_dtype=jnp.bfloat16,
            )

    # One-ahead prefetch: pano decode+resize (hundreds of ms of host work at
    # 3200 px) overlaps the device forward of the previous pano.
    from concurrent.futures import ThreadPoolExecutor

    def load_pano(pano_fn):
        arr = load_inloc_image(
            os.path.join(args.pano_path, pano_fn), args.image_size, args.k_size,
            extra_align=args.spatial_shards, feat_unit=args.feat_unit,
        )
        # --pano_dp stacks on the HOST and device_puts the stack sharded
        # (per-device H2D); everything else moves each pano to the device
        # as soon as it decodes so H2D overlaps compute.
        return arr if args.pano_dp else jnp.asarray(arr)

    def pano_target_shape(pano_fn):
        """Resized (H, W) bucket from the image HEADER alone — a cache
        hit must not pay the 3200 px decode."""
        from PIL import Image

        with Image.open(os.path.join(args.pano_path, pano_fn)) as im:
            w, h = im.size
        h_unit, w_unit = resolve_feat_units(
            args.feat_unit, args.image_size, args.k_size, args.spatial_shards
        )
        return inloc_resize_shape(
            h, w, args.image_size, args.k_size, h_unit=h_unit, w_unit=w_unit
        )

    def prepare_pano(pano_fn):
        """Prefetch-thread work: cache probe (header-only) and, on a
        miss, the full decode. Returns (shape, cached_feats_or_None,
        decoded_image_or_None)."""
        shape = pano_target_shape(pano_fn)
        feats = cache.get(os.path.join(args.pano_path, pano_fn), shape)
        if feats is not None:
            return shape, feats, None
        return shape, None, load_pano(pano_fn)

    from ..utils.profiling import trace_context

    pool = ThreadPoolExecutor(
        max_workers=2 if (args.pano_batch > 1 or cache is not None) else 1
    )
    if args.pano_dp:
        batch_fn, stack_fn = pano_matches_dp, dp_stack
    else:
        batch_fn = pano_matches_batch if args.pano_batch > 1 else None
        stack_fn = None
    cache_fns = (
        (prepare_pano, match_from_cached_feats, pano_matches_with_feats,
         pano_matches_batch_with_feats)
        if cache is not None else None
    )
    t_loop = time.perf_counter()
    try:
        with trace_context(args.profile_dir):
            _query_loop(args, db, out_dir, params, query_features, pano_matches,
                        n_matches, pano_fn_all, pool, load_pano, batch_fn,
                        cache=cache, cache_fns=cache_fns, stack_fn=stack_fn)
    except BaseException as exc:
        if run_log is not None:
            run_log.close(f"error:{type(exc).__name__}")
            run_log = None
        raise
    finally:
        pool.shutdown(wait=False, cancel_futures=True)
    elapsed = time.perf_counter() - t_loop
    pairs = obs.counter("eval_inloc.pairs").value
    if elapsed > 0:
        obs.gauge("eval_inloc.pairs_per_s").set(pairs / elapsed)
    if cache is not None:
        print(cache.stats(), flush=True)
        obs.gauge("eval_inloc.cache.hits").set(cache.hits)
        obs.gauge("eval_inloc.cache.misses").set(cache.misses)
        obs.gauge("eval_inloc.cache.disk_hits").set(cache.disk_hits)
        obs.event("cache_stats", stats=cache.stats(), hits=cache.hits,
                  misses=cache.misses, disk_hits=cache.disk_hits)
    if run_log is not None:
        run_log.flush_metrics(phase="matching")
        run_log.close("ok", pairs=pairs, elapsed_s=elapsed)
    return out_dir




def _run_panos_batched(args, params, feat_a, batch_fn, buf, pano_fns, pool,
                       load_pano, stack_fn=None):
    """All of one query's panos in same-shape stacks of --pano_batch.

    Ragged dispatch is the default (`NCNET_RAGGED_MISS_STACKS=1`, see
    `_ragged_miss_stacks` / `_MissGroups`): partial groups run at their
    TRUE size, one extra jit retrace per distinct size. With
    `NCNET_RAGGED_MISS_STACKS=0` — and ALWAYS under `--pano_dp`
    (`stack_fn` set), whose sharded device_put needs stacks divisible
    by the mesh — ragged groups fall back to padding by repeating their
    last pano (the padded iterations' outputs are discarded), so each
    bucket shape compiles exactly one program regardless of how the
    shortlist's shapes mix.
    """
    p = args.pano_batch
    n = len(pano_fns)
    # Sliding decode window: at most p+1 loads in flight. Decoded images
    # ALSO accumulate in partially-filled shape buckets (_MissGroups),
    # so the true host bound is the decode window plus the bucket cap
    # (2p): ~3p decoded panos total, regardless of how many distinct
    # shapes interleave.
    window = p + 1
    futures = {
        i: pool.submit(load_pano, pano_fns[i]) for i in range(min(window, n))
    }

    def flush(idxs, ms):
        np_ms = jax.device_get(ms)
        for k, idx in enumerate(idxs):
            fill_matches(buf, idx, dedup_matches(*(a[k] for a in np_ms)))

    pending = None  # one-behind: dispatch next stack before fetching prior

    # --pano_dp (stack_fn set) MUST pad: its device_put shards the stack
    # over the dp mesh, and a ragged partial group's leading dim is not
    # divisible by the mesh size (ADVICE r5 high).
    ragged = _ragged_miss_stacks() and stack_fn is None

    def dispatch(chunk):
        nonlocal pending
        obs.counter("eval_inloc.dispatch.ragged" if len(chunk) < p and ragged
                    else "eval_inloc.dispatch.padded" if len(chunk) < p
                    else "eval_inloc.dispatch.full").inc()
        if len(chunk) < p and not ragged:
            obs.counter("eval_inloc.pad_slots").inc(p - len(chunk))
        imgs = [img for _, img in (chunk if ragged else groups.pad(chunk))]
        stack = (
            stack_fn(imgs) if stack_fn is not None
            else jnp.concatenate(imgs, axis=0)
        )
        ms = batch_fn(params, feat_a, stack)
        if pending is not None:
            flush(*pending)
        # Keep only indices + device handles: the host image copies are
        # dropped here, bounding host/device memory to ~p images per
        # in-flight group instead of the whole shortlist.
        pending = ([idx for idx, _ in chunk], ms)

    groups = _MissGroups(p, dispatch)
    # Incremental grouping: a stack dispatches the moment p same-shape
    # panos have decoded, so decode (threaded, hundreds of ms at 3200 px)
    # overlaps the device forward of the previous stack — same pipelining
    # property as the unbatched one-behind loop.
    for idx in range(n):
        img = futures.pop(idx).result()
        nxt = idx + window
        if nxt < n:
            futures[nxt] = pool.submit(load_pano, pano_fns[nxt])
        groups.add(img.shape[2:], (idx, img))
    groups.drain()
    if pending is not None:
        flush(*pending)


def _run_panos_cached_batched(args, params, feat_a, buf, pano_fns, pool,
                              cache, cache_fns):
    """--pano_batch composed with the cross-query feature cache.

    The grouping/padding/backlog heuristics are `_MissGroups` — the
    same object `_run_panos_batched` drives, so the two modes cannot
    drift. Hits dispatch immediately per pano (a hit has no backbone to
    batch, and the consensus stack runs batch-1 in every mode); misses
    accumulate into same-shape stacks of --pano_batch and run the
    batched-backbone miss program, which also returns the stack's bf16
    features for the store. This keeps the promoted batched-backbone
    miss cost (bb5: 9.69 vs 6.09 pairs/s on v5e) in cached runs;
    without it, every cached miss would pay a per-pano backbone and the
    cache would LOSE to plain --pano_batch below ~70% hit-rate.
    """
    prepare_pano, match_cached, _, batch_with_feats = cache_fns
    p = args.pano_batch
    n = len(pano_fns)
    window = p + 1
    futures = {
        i: pool.submit(prepare_pano, pano_fns[i])
        for i in range(min(window, n))
    }
    pending = None  # ("hit", idx, ms) | ("miss", idxs, ms)
    put_futs = []

    def flush(entry):
        if entry[0] == "hit":
            fill_matches(buf, entry[1], dedup_matches(*entry[2]))
            return
        _, idxs, ms = entry
        np_ms = jax.device_get(ms)
        for k, idx in enumerate(idxs):
            fill_matches(buf, idx, dedup_matches(*(a[k] for a in np_ms)))

    ragged = _ragged_miss_stacks()

    def dispatch_miss(chunk):
        nonlocal pending
        obs.counter("eval_inloc.dispatch.ragged" if len(chunk) < p and ragged
                    else "eval_inloc.dispatch.padded" if len(chunk) < p
                    else "eval_inloc.dispatch.full").inc()
        if len(chunk) < p and not ragged:
            obs.counter("eval_inloc.pad_slots").inc(p - len(chunk))
        stack = jnp.concatenate(
            [img for _, _, img in (chunk if ragged else groups.pad(chunk))],
            axis=0,
        )
        ms, feats = batch_with_feats(params, feat_a, stack)
        if pending is not None:
            flush(pending)
        pending = ("miss", [idx for idx, _, _ in chunk], ms)
        for k, (idx, shape, _) in enumerate(chunk):
            # feats[k] is a device slice; put()'s np.asarray is the D2H
            # fetch, on the pool thread so the device keeps working.
            put_futs.append(pool.submit(
                cache.put, os.path.join(args.pano_path, pano_fns[idx]),
                shape, feats[k],
            ))

    groups = _MissGroups(p, dispatch_miss)
    for idx in range(n):
        shape, feats_np, img = futures.pop(idx).result()
        nxt = idx + window
        if nxt < n:
            futures[nxt] = pool.submit(prepare_pano, pano_fns[nxt])
        if feats_np is not None:
            ms = match_cached(params, feat_a, jnp.asarray(feats_np))
            if pending is not None:
                flush(pending)
            pending = ("hit", idx, ms)
            continue
        groups.add(tuple(img.shape[2:]), (idx, shape, img))
    groups.drain()
    if pending is not None:
        flush(pending)
    # Drain this query's stores before the next query probes (same
    # contract as the sequential cached loop).
    for f in put_futs:
        f.result()


def _run_panos_cached(args, params, feat_a, buf, pano_fns, pool, cache,
                      cache_fns):
    """Per-pano loop with the cross-query feature cache.

    Same one-behind pipelining as the uncached loop; the prefetch thread
    additionally probes the cache from the image header alone, so a hit
    skips BOTH the pano backbone and the 3200 px host decode. Misses run
    a program that also returns the pano features; the D2H fetch + store
    happen on the pool thread so the device keeps working.
    """
    prepare_pano, match_cached, matches_with_feats, _ = cache_fns
    n = len(pano_fns)
    fut = pool.submit(prepare_pano, pano_fns[0]) if pano_fns else None
    pending = None  # (pano_idx, device match tuple)
    put_futs = []
    for idx in range(n):
        shape, feats_np, tgt = fut.result()
        if idx + 1 < n:
            fut = pool.submit(prepare_pano, pano_fns[idx + 1])
        if feats_np is not None:
            dev_matches = match_cached(params, feat_a, jnp.asarray(feats_np))
        else:
            dev_matches, feat_b = matches_with_feats(params, feat_a, tgt)
            # put() np.asarray()s the device handle = the D2H fetch;
            # running it on the pool thread keeps the main loop async.
            put_futs.append(pool.submit(
                cache.put, os.path.join(args.pano_path, pano_fns[idx]),
                shape, feat_b,
            ))
        if pending is not None:
            fill_matches(buf, pending[0], dedup_matches(*pending[1]))
        pending = (idx, dev_matches)
        if idx % 10 == 0:
            print(f">>> query pano {idx}", flush=True)
    if pending is not None:
        fill_matches(buf, pending[0], dedup_matches(*pending[1]))
    # Drain this query's stores before the next query probes: a put still
    # in flight would turn the next query's hit into a spurious miss
    # (recompute + double store) and make hit rates nondeterministic.
    for f in put_futs:
        f.result()


def _query_loop(args, db, out_dir, params, query_features, pano_matches,
                n_matches, pano_fn_all, pool, load_pano, batch_fn=None,
                cache=None, cache_fns=None, stack_fn=None):
    for q in range(min(args.n_queries, len(db))):
        out_path = os.path.join(out_dir, f"{q + 1}.mat")
        if args.resume and os.path.exists(out_path):
            obs.counter("eval_inloc.queries_skipped").inc()
            continue
        query_fn = db[q][0].item()

        def _query_done():
            obs.counter("eval_inloc.queries").inc()
            obs.counter("eval_inloc.pairs").inc(args.n_panos)

        # One trace per query (obs/trace.py): the per-query wall time
        # decomposes into query_features + panos children the same way
        # a serving request decomposes into admit/queue/device. The
        # trace root IS the per-query `query` span event (same fields
        # the flat v1 event carried, plus the trace ids).
        with obs.trace.trace("query", q=q, query_fn=query_fn,
                             n_panos=args.n_panos):
            # No sync=: the query forward is intentionally async-dispatch
            # (the one-behind pipeline overlaps it); this span measures
            # host decode + dispatch, not device completion.
            with obs.trace.span("query_features"):
                src = jnp.asarray(
                    load_inloc_image(
                        os.path.join(args.query_path, query_fn),
                        args.image_size, args.k_size,
                        extra_align=args.spatial_shards,
                        feat_unit=args.feat_unit,
                    )
                )
                feat_a = query_features(params, src)
            buf = matches_buffer(args.n_panos, n_matches)
            pano_fns = [db[q][1].ravel()[i].item()
                        for i in range(args.n_panos)]
            if cache is not None and batch_fn is not None:
                # --pano_batch + cache: hits per-pano, misses in batched
                # stacks through the batched-with-feats program.
                with obs.trace.span("panos", mode="cached_batched"):
                    _run_panos_cached_batched(args, params, feat_a, buf,
                                              pano_fns, pool, cache,
                                              cache_fns)
                write_matches_mat(out_path, buf, query_fn, pano_fn_all)
                print(f"wrote {out_path}", flush=True)
                _query_done()
                continue
            if batch_fn is not None:
                with obs.trace.span("panos", mode="batched"):
                    _run_panos_batched(args, params, feat_a, batch_fn, buf,
                                       pano_fns, pool, load_pano,
                                       stack_fn=stack_fn)
                write_matches_mat(out_path, buf, query_fn, pano_fn_all)
                print(f"wrote {out_path}", flush=True)
                _query_done()
                continue
            if cache is not None:
                with obs.trace.span("panos", mode="cached"):
                    _run_panos_cached(args, params, feat_a, buf, pano_fns,
                                      pool, cache, cache_fns)
                write_matches_mat(out_path, buf, query_fn, pano_fn_all)
                print(f"wrote {out_path}", flush=True)
                _query_done()
                continue
            with obs.trace.span("panos", mode="pipelined"):
                fut = pool.submit(load_pano, pano_fns[0]) if pano_fns else None
                # One-behind host processing: pano idx's forward is
                # dispatched (async) BEFORE pano idx-1's matches are
                # fetched and deduped, so the device-side forward overlaps
                # both the host dedup and the fetch's tunnel round trip
                # instead of idling through them.
                pending = None  # (pano_idx, device match tuple)
                for idx in range(args.n_panos):
                    tgt = fut.result()
                    if idx + 1 < args.n_panos:
                        fut = pool.submit(load_pano, pano_fns[idx + 1])
                    dev_matches = pano_matches(params, feat_a, tgt)
                    if pending is not None:
                        fill_matches(buf, pending[0],
                                     dedup_matches(*pending[1]))
                    pending = (idx, dev_matches)
                    if idx % 10 == 0:
                        print(f">>> query {q} pano {idx}", flush=True)
                if pending is not None:
                    fill_matches(buf, pending[0], dedup_matches(*pending[1]))
            write_matches_mat(out_path, buf, query_fn, pano_fn_all)
            print(f"wrote {out_path}", flush=True)
            _query_done()


if __name__ == "__main__":
    main()
