"""Elastic training driver: survive a host loss, resume at step.

The membership plane (parallel/membership.py) says WHO is in the run;
this module makes the training loop ACT on it. One
:class:`ElasticDriver` wraps a step loop (cli/train.py's, or the chaos
harness's synthetic one):

* every step calls :meth:`ElasticDriver.step_check` — a time-gated
  membership probe (default every 0.25 s, so steady-state overhead is
  a dict read, not a filesystem scan per step) that surfaces heartbeat
  failures, picks up a newer generation written by a peer, and runs
  dead-host detection. A detected death bumps the generation (shrink)
  and raises :class:`MembershipChange`;

* the trainer catches :class:`MembershipChange`, reloads the last
  committed checkpoint (``training/checkpoint.py``'s fallback walk),
  calls :meth:`ElasticDriver.resume`, re-derives its batch slice from
  the NEW generation via :meth:`slice_for` /
  :func:`adjusted_global_batch` (round down + ``train_batch_adjusted``
  event when the shrunk host count no longer divides), fast-forwards
  the deterministic loader to the checkpointed step, and continues;

* every trained step is appended to a per-host **step ledger**
  (``steps-<host>.jsonl``, line-buffered appends) recording
  ``(generation, epoch, step, slice)`` — the zero-silent-step-loss
  audit replays these and checks that every step of the final curve is
  tiled by SOME generation's slices (tools/chaos_train.py).

Caveat for real multi-process JAX pods: ``jax.distributed`` cannot
reshape a live process set, so there the driver's job is detect →
durable bump → exit-for-relaunch (the relaunched gang re-forms at the
new generation and resumes from the same checkpoint chain); the
continue-in-process path below is for the one-JAX-process-per-host
harness mode (cli/train.py --elastic_dir, tools/chaos_train.py).

Metrics (docs/OBSERVABILITY.md): ``train.generation`` /
``train.hosts_live`` gauges, ``train.resumes`` / ``train.lost_steps``
counters — booked through obs/train_watch.py so a fleet merge sees
them next to the step beacons. Failpoint: ``elastic.resume`` fires at
resume entry (error = a resume crash drill; kill = dying mid-resume,
which must be re-survivable).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, List, Optional, Tuple

from .. import obs
from ..obs import train_watch
from ..parallel import membership as _membership
from ..parallel import multihost
from ..reliability import failpoints


class MembershipChange(RuntimeError):
    """The generation moved (a host died or rejoined) and this host is
    still a member: reload the last committed checkpoint, adopt
    ``record`` via :meth:`ElasticDriver.resume`, and continue."""

    def __init__(self, record: dict, dead: Optional[List[str]] = None,
                 epoch: Optional[int] = None, step: Optional[int] = None):
        super().__init__(
            f"membership changed: generation {record.get('generation')} "
            f"hosts {record.get('hosts')}"
            + (f" (detected dead: {dead})" if dead else "")
        )
        self.record = record
        self.dead = list(dead or [])
        #: Where THIS host was when the change surfaced (feeds the
        #: lost-step accounting in :meth:`ElasticDriver.resume`).
        self.epoch = epoch
        self.step = step


def adjusted_global_batch(requested: int, n_hosts: int) -> int:
    """Round the global batch DOWN to a multiple of the live host count.

    A 3-host batch of 16 cannot survive a shrink to 2 hosts unchanged;
    rather than abort, the elastic driver trains the largest divisible
    batch and says so (``train_batch_adjusted`` event). Raises when
    even one row per host does not fit.
    """
    n_hosts = int(n_hosts)
    if n_hosts < 1:
        raise ValueError(f"host count must be >= 1, got {n_hosts}")
    adjusted = (int(requested) // n_hosts) * n_hosts
    if adjusted < n_hosts:
        raise ValueError(
            f"global batch {requested} cannot cover {n_hosts} hosts "
            "with at least one row each"
        )
    if adjusted != requested:
        obs.event("train_batch_adjusted", requested=int(requested),
                  adjusted=adjusted, hosts=n_hosts)
    return adjusted


class ElasticDriver:
    """Membership-aware wrapper around one host's training loop.

    Single-threaded by design: every method is called from the
    training thread. The only companion thread is the
    :class:`~..parallel.membership.LeaseHeartbeat`, which communicates
    exclusively through its own lock (``error()``/``update()``).
    """

    def __init__(
        self,
        plane: _membership.MembershipPlane,
        check_interval_s: float = 0.25,
        heartbeat_s: Optional[float] = None,
        ledger_dir: Optional[str] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.plane = plane
        self.check_interval_s = float(check_interval_s)
        # Default heartbeat: renew well inside the TTL. 0 disables the
        # thread (unit tests renew inline from step_check instead).
        self._heartbeat_s = (
            heartbeat_s if heartbeat_s is not None
            else max(plane.lease_ttl_s / 4.0, 0.05)
        )
        self._clock = clock
        self._record: Optional[dict] = None
        self._hb: Optional[_membership.LeaseHeartbeat] = None
        self._last_check = float("-inf")
        self._committed: Tuple[int, int] = (1, 0)
        self.resumes = 0
        self.lost_steps = 0
        #: Cumulative seconds spent inside step_check's slow path —
        #: the lease/heartbeat overhead bench_train --hosts reports
        #: against total step time (< 2% acceptance line).
        self.check_time_s = 0.0
        self._ledger_fh = None
        if ledger_dir:
            os.makedirs(ledger_dir, exist_ok=True)
            self._ledger_fh = open(
                os.path.join(ledger_dir, f"steps-{plane.host}.jsonl"),
                "a", encoding="utf-8")

    # -- membership view ---------------------------------------------------

    @property
    def record(self) -> dict:
        if self._record is None:
            raise _membership.MembershipError("driver not started")
        return self._record

    @property
    def generation(self) -> int:
        return int(self.record["generation"])

    @property
    def hosts(self) -> List[str]:
        return list(self.record["hosts"])

    @property
    def n_hosts(self) -> int:
        return len(self.record["hosts"])

    @property
    def rank(self) -> int:
        """This host's rank = its position in the generation's sorted
        host list; rank 0 is the checkpoint writer (writer takeover on
        a shrink that removes the old rank 0 is automatic)."""
        return self.record["hosts"].index(self.plane.host)

    @property
    def is_writer(self) -> bool:
        return self.rank == 0

    def slice_for(self, global_batch_size: int) -> Tuple[int, int]:
        """This generation's ``host_local_slice`` of the global batch."""
        return multihost.host_local_slice(
            global_batch_size, rank=self.rank, n_hosts=self.n_hosts)

    # -- lifecycle ---------------------------------------------------------

    def start(self, step: int = 0) -> "ElasticDriver":
        """Join the current generation and start heartbeating."""
        self._record = self.plane.join(step=step)
        if self._heartbeat_s > 0:
            self._hb = _membership.LeaseHeartbeat(
                self.plane, interval_s=self._heartbeat_s
            ).start(self.generation, step)
        self._book_membership()
        return self

    def stop(self) -> None:
        if self._hb is not None:
            self._hb.stop()
            self._hb = None
        if self._ledger_fh is not None:
            self._ledger_fh.close()
            self._ledger_fh = None
        self.plane.drop_lease()

    def note_commit(self, epoch: int, step: int) -> None:
        """Record the last COMMITTED checkpoint position; a bump's
        resume marker advertises it so lost-step accounting and the
        chaos audit know where survivors restarted."""
        self._committed = (int(epoch), int(step))

    def commit_barrier(self, epoch: int, step: int,
                       wait_s: Optional[float] = None) -> bool:
        """May the writer commit a checkpoint at ``(epoch, step)``?

        Only once every live member's lease advertises a position at or
        past it — the harness stand-in for "the collective completed
        this step on every host". Without it a writer could commit a
        position a since-dead host never contributed to, and the steps
        between that host's death and its detection would be silently
        lost (the resume marker would sit PAST them).

        Advertised positions lag by up to one heartbeat, so like a real
        collective the writer WAITS (default: three heartbeats) for
        live peers to cross the target; a peer that never does within
        the wait — dead, or wedged — fails the barrier and the save is
        skipped (detection then evicts it). A stale lease understates
        progress, so the barrier can delay a commit, never admit an
        unsafe one.
        """
        if wait_s is None:
            wait_s = 3.0 * self._heartbeat_s
        target = (int(epoch), int(step))
        deadline = self._clock() + max(wait_s, 0.0)
        while True:
            leases = self.plane.live_view()
            behind = None
            for host in self.hosts:
                if host == self.plane.host:
                    continue
                lease = leases.get(host)
                pos = ((int(lease.get("epoch", 0)),
                        int(lease.get("step", 0)))
                       if lease is not None else (-1, -1))
                if pos < target:
                    behind = host
                    break
            if behind is None:
                return True
            if self._clock() >= deadline:
                return False
            time.sleep(0.01)

    def advertise(self, epoch: int, step: int) -> None:
        """Push this host's training position toward the gang out of
        band (the next heartbeat carries it; written immediately when
        no heartbeat thread runs)."""
        if self._hb is not None:
            self._hb.update(self.generation, step, epoch=epoch)
        else:
            self.plane.renew(self.generation, step=step, epoch=epoch)

    def finish_barrier(self, num_epochs: int,
                       wait_s: float = 600.0) -> bool:
        """Block a host that COMPLETED the run until its gang peers are
        done too, so its expiring lease is not mistaken for a mid-run
        death (peers would bump and replay the tail epoch for nothing).

        Advertises ``(num_epochs + 1, 0)`` — past any trainable
        position — then waits until every peer is *finished* (its lease
        advertises the same), *departed* (lease dropped: a clean exit),
        or *dead* (lease stale past the TTL: no point waiting). The
        heartbeat keeps renewing throughout, so a straggler never
        evicts the waiter; the wait is bounded only by ``wait_s`` as a
        wedge backstop — a live peer either advances or goes stale
        within one TTL.
        """
        target = (int(num_epochs) + 1, 0)
        self.advertise(target[0], target[1])
        deadline = self._clock() + max(wait_s, 0.0)
        while True:
            leases = self.plane.live_view()
            now = self.plane._clock()
            waiting = None
            for host in self.hosts:
                if host == self.plane.host:
                    continue
                lease = leases.get(host)
                if lease is None:
                    continue  # departed: clean drop on exit
                if (int(lease.get("epoch", 0)),
                        int(lease.get("step", 0))) >= target:
                    continue  # finished
                if now - float(lease.get("t", 0.0)) \
                        > self.plane.lease_ttl_s:
                    continue  # dead: nothing to wait for
                waiting = host
                break
            if waiting is None:
                return True
            if self._clock() >= deadline:
                return False
            time.sleep(0.05)

    # -- the per-step probe ------------------------------------------------

    def step_check(self, epoch: int, step: int, force: bool = False) -> None:
        """Membership probe for one training step (time-gated).

        Fast path: one clock read. Slow path (every
        ``check_interval_s``): surface heartbeat errors, renew inline
        when no heartbeat thread runs, re-read the generation record,
        detect dead hosts and bump. Raises :class:`MembershipChange`
        (still a member; resume and continue) or
        :class:`~..parallel.membership.StaleGenerationError` (evicted).
        """
        now = self._clock()
        if not force and now - self._last_check < self.check_interval_s:
            return
        self._last_check = now
        try:
            # Generation first: a peer's bump must surface as a
            # MembershipChange BEFORE this host renews or detects at
            # the generation it still holds.
            rec = self.plane.read_generation()
            if rec is not None and rec["generation"] > self.generation:
                if self.plane.host not in rec["hosts"]:
                    raise _membership.StaleGenerationError(
                        self.plane.host, self.generation, rec)
                raise MembershipChange(rec, epoch=epoch, step=step)
            if self._hb is not None:
                self._hb.update(self.generation, step, epoch=epoch)
                err = self._hb.error()
                if err is not None:
                    raise err
            else:
                self.plane.renew(self.generation, step=step, epoch=epoch)
            dead = self.plane.detect_dead(rec)
            if dead:
                survivors = [h for h in self.hosts if h not in dead]
                new = self.plane.bump(
                    survivors,
                    resume_epoch=self._committed[0],
                    resume_step=self._committed[1],
                    expected_generation=self.generation,
                )
                if self.plane.host not in new["hosts"]:
                    raise _membership.StaleGenerationError(
                        self.plane.host, self.generation, new)
                raise MembershipChange(new, dead=dead, epoch=epoch, step=step)
        finally:
            self.check_time_s += self._clock() - now

    # -- resume ------------------------------------------------------------

    def resume(self, record: dict, resumed_epoch: int, resumed_step: int,
               detected_epoch: int, detected_step: int,
               steps_per_epoch: int) -> None:
        """Adopt a new generation after reloading the checkpoint.

        ``resumed_*`` is the checkpoint position training restarts
        from, ``detected_*`` where this host was when the change
        surfaced; the difference is this host's re-trained ("lost")
        steps — bounded by the save interval plus the detection window,
        never silent.
        """
        failpoints.fire("elastic.resume", payload=record.get("generation"))
        lost = max(
            (int(detected_epoch) - int(resumed_epoch)) * int(steps_per_epoch)
            + int(detected_step) - int(resumed_step), 0)
        self._record = record
        self.resumes += 1
        self.lost_steps += lost
        if self._hb is not None:
            self._hb.update(self.generation, resumed_step,
                            epoch=resumed_epoch)
        else:
            self.plane.renew(self.generation, step=resumed_step,
                             epoch=resumed_epoch)
        train_watch.book_resume(self.generation, lost)
        self._book_membership()
        obs.event(
            "elastic_resume", generation=self.generation, hosts=self.hosts,
            host=self.plane.host, rank=self.rank,
            resumed_epoch=int(resumed_epoch), resumed_step=int(resumed_step),
            detected_epoch=int(detected_epoch),
            detected_step=int(detected_step), lost_steps=lost,
        )

    def _book_membership(self) -> None:
        train_watch.book_membership(self.generation, self.n_hosts)

    # -- step ledger -------------------------------------------------------

    def record_step(self, epoch: int, step: int,
                    batch_slice: Optional[Tuple[int, int]] = None) -> None:
        """Append one trained step to this host's ledger (flushed per
        line: after a SIGKILL the ledger is complete up to the last
        finished step, which is exactly what the audit replays)."""
        if self._ledger_fh is None:
            return
        rec = {
            "gen": self.generation,
            "epoch": int(epoch),
            "step": int(step),
            "host": self.plane.host,
        }
        if batch_slice is not None:
            rec["slice"] = [int(batch_slice[0]), int(batch_slice[1])]
        self._ledger_fh.write(json.dumps(rec, sort_keys=True) + "\n")
        self._ledger_fh.flush()
