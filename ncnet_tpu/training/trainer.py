"""Training: optax state, jitted data-parallel train/eval steps.

Reference parity (train.py of the reference tree):
  * Adam, lr 5e-4 (train.py:41,71), batch 16, 5 epochs;
  * only the NeighConsensus stack is trainable — the backbone is frozen
    (lib/model.py:75-78) and stays in inference mode (lib/model.py:251);
  * per-epoch validation on val_pairs.csv with best-checkpoint tracking
    (train.py:191-206).

TPU-first design: the step is one jit containing both forward passes
(positive + rolled negative) and the update; data parallelism is expressed
by sharding the batch over the mesh 'dp' axis with NamedShardings — XLA
inserts the gradient allreduce over ICI. The frozen backbone params are
donated/replicated constants.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..models.ncnet import (
    NCNetConfig,
    extract_features,
    ncnet_forward_from_features,
)
from .loss import weak_loss_from_features

Params = Dict[str, Any]


@dataclasses.dataclass
class TrainState:
    """Pure-pytree train state (params split by trainability)."""

    trainable: Params  # neigh_consensus (+ optionally fine-tuned backbone)
    frozen: Params  # backbone
    opt_state: Any
    step: int = 0

    def full_params(self) -> Params:
        return {"backbone": self.frozen["backbone"], **self.trainable}


def _finetune_mask(backbone: Params, n_blocks: int) -> Params:
    """Update-mask over the backbone: True only for the last `n_blocks`
    blocks' weights, excluding batch-norm running statistics.

    Mirrors the reference's fine-tune selection (train.py:60-63: the last N
    children of the last stage get requires_grad=True — their conv weights
    and BN affine params, but never the running mean/var, which are buffers).
    """

    def false_like(t):
        return jax.tree.map(lambda _: False, t)

    mask = false_like(backbone)
    if n_blocks <= 0:
        return mask

    def block_mask(block):
        m = false_like(block)
        for k, v in block.items():
            if k.startswith("conv"):
                m[k] = True
            elif k.startswith("bn"):
                m[k] = {"scale": True, "bias": True, "mean": False, "var": False}
            elif k == "downsample":
                m[k] = {
                    "conv": True,
                    "bn": {"scale": True, "bias": True, "mean": False, "var": False},
                }
        return m

    if "layers" in backbone:  # vgg: last n conv layers
        conv_idx = [i for i, l in enumerate(backbone["layers"]) if l]
        for i in conv_idx[-n_blocks:]:
            mask["layers"][i] = {"w": True, "b": True}
    else:  # resnet: last n bottleneck blocks of the last stage
        last_stage = max(k for k in backbone if k.startswith("layer"))
        blocks = backbone[last_stage]
        for i in range(max(len(blocks) - n_blocks, 0), len(blocks)):
            mask[last_stage][i] = block_mask(blocks[i])
    return mask


def create_train_state(
    params: Params,
    learning_rate: float = 5e-4,
    train_fe: bool = False,
    fe_finetune_blocks: int = 1,
) -> Tuple[TrainState, optax.GradientTransformation]:
    """Split params into trainable/frozen and init Adam.

    With train_fe=False only the NeighConsensus stack receives gradients,
    mirroring the reference's requires_grad freeze (lib/model.py:75-78).
    With train_fe=True the backbone joins the trainable set but the Adam
    update is masked to the last `fe_finetune_blocks` blocks' weights —
    batch-norm running statistics are never updated (they are buffers, not
    parameters).
    """
    if train_fe:
        trainable = {
            "neigh_consensus": params["neigh_consensus"],
            "backbone": params["backbone"],
        }
        frozen = {"backbone": params["backbone"]}  # forward uses trainable's
        mask = {
            "neigh_consensus": jax.tree.map(
                lambda _: True, params["neigh_consensus"]
            ),
            "backbone": _finetune_mask(params["backbone"], fe_finetune_blocks),
        }
        labels = jax.tree.map(lambda m: "train" if m else "freeze", mask)
        tx = optax.multi_transform(
            {"train": optax.adam(learning_rate), "freeze": optax.set_to_zero()},
            labels,
        )
    else:
        trainable = {"neigh_consensus": params["neigh_consensus"]}
        frozen = {"backbone": params["backbone"]}
        tx = optax.adam(learning_rate)
    opt_state = tx.init(trainable)
    return TrainState(trainable, frozen, opt_state, 0), tx


def make_train_step(
    config: NCNetConfig,
    tx: optax.GradientTransformation,
    normalization: str = "softmax",
    remat_backbone: bool = False,
    accum_steps: int = 1,
):
    """Build the jitted train step (loss + grads + Adam update).

    ``train_step`` returns ``(trainable, opt_state, loss, aux)`` where
    ``aux`` holds the device-scalar health signals (``grad_norm``,
    ``update_ratio``) the training observatory resolves lazily.

    remat_backbone=True wraps feature extraction in jax.checkpoint so its
    activations are recomputed in the backward pass instead of stored —
    the HBM lever for fine-tuning the backbone (train_fe) at high
    resolution / large batch; with the default frozen backbone there is no
    backbone backward pass and remat only costs compute.

    accum_steps=k > 1 gradient-accumulates over k sequential micro-batches
    of batch/k pairs (lax.scan, so XLA keeps ONE micro-batch of AD
    activations live — the direct HBM lever for the reference's batch-16
    schedule, complementary to the remat policies). Loss and grads are
    the MEAN over micro-batches. Note the weak loss forms its negatives
    by rolling WITHIN a batch (loss.py): with accumulation the roll pairs
    within each micro-batch, so the negative set differs from the
    unaccumulated batch — same loss family, not bit-identical training.
    The batch size must divide by k.
    """
    # Record how the step was built once, host-side: the grad-accum /
    # remat choice decides both HBM shape and which remat default fires,
    # so every run log carries it (obs no-ops without an active run; the
    # gauges surface in the first metrics snapshot either way).
    obs.event("train_step_build", accum_steps=accum_steps,
              remat_backbone=remat_backbone, normalization=normalization)
    obs.gauge("train.accum_steps").set(accum_steps)
    obs.gauge("train.remat_backbone").set(1.0 if remat_backbone else 0.0)

    def loss_fn(trainable: Params, frozen: Params, source, target):
        params = {
            "backbone": trainable.get("backbone", frozen["backbone"]),
            "neigh_consensus": trainable["neigh_consensus"],
        }

        features = extract_features
        if remat_backbone:
            features = jax.checkpoint(
                extract_features, static_argnums=(0,), policy=None
            )
        feat_a = features(config, params, source)
        feat_b = features(config, params, target)

        def match(fa, fb):
            corr, _ = ncnet_forward_from_features(config, params, fa, fb)
            return corr

        # Remat default per path (hardware-measured, see loss.py): a
        # micro-batch of <= 4 pairs fits un-rematerialized ("none",
        # 4.5 s/step at batch 16 x accum 4 on v5e) where the batch-16
        # AD fails to compile and must save dots ("dots", 5.4 s/step).
        # Larger micro-batches are unmeasured between those endpoints,
        # so only the measured size gets the aggressive default;
        # NCNET_TRAIN_REMAT_POLICY overrides. feat_a's leading dim IS
        # the micro-batch at trace time (the accum path scans over
        # micro-slices before calling loss_fn).
        micro = feat_a.shape[0]
        return weak_loss_from_features(
            match, feat_a, feat_b, normalization,
            remat_policy="none" if accum_steps > 1 and micro <= 4
            else "dots",
        )

    # Donate the updated-in-place buffers (params + opt state): XLA reuses
    # their device memory for the outputs instead of allocating fresh copies
    # each step.
    @partial(jax.jit, donate_argnums=(0, 2))
    def train_step(state_trainable, state_frozen, opt_state, source, target):
        if accum_steps > 1:
            b = source.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch size {b} not divisible by accum_steps "
                    f"{accum_steps}"
                )
            micro = b // accum_steps
            if micro < 2:
                raise ValueError(
                    "micro-batch of 1: the weak loss forms negatives by "
                    "rolling WITHIN a micro-batch (loss.py), so batch/"
                    f"accum_steps must be >= 2 (got batch {b}, accum "
                    f"{accum_steps}) — training would be silently dead"
                )
            msrc = source.reshape(accum_steps, micro, *source.shape[1:])
            mtgt = target.reshape(accum_steps, micro, *target.shape[1:])

            def body(carry, xs):
                g_acc, l_acc = carry
                s, t = xs
                loss, grads = jax.value_and_grad(loss_fn)(
                    state_trainable, state_frozen, s, t
                )
                g_acc = jax.tree.map(jnp.add, g_acc, grads)
                return (g_acc, l_acc + loss), None

            zeros = jax.tree.map(jnp.zeros_like, state_trainable)
            (g_sum, l_sum), _ = jax.lax.scan(
                body, (zeros, jnp.float32(0.0)), (msrc, mtgt)
            )
            grads = jax.tree.map(lambda g: g / accum_steps, g_sum)
            loss = l_sum / accum_steps
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                state_trainable, state_frozen, source, target
            )
        updates, new_opt_state = tx.update(grads, opt_state, state_trainable)
        new_trainable = optax.apply_updates(state_trainable, updates)
        # Divergence/health telemetry for obs.train_watch: the global
        # grad norm and the update/param scale ratio come out as device
        # scalars — free inside the jit (the norms reuse live buffers),
        # fetched host-side only by the bounded-lag sentinel.
        aux = {
            "grad_norm": optax.global_norm(grads),
            "update_ratio": optax.global_norm(updates)
            / (optax.global_norm(state_trainable) + 1e-12),
        }
        return new_trainable, new_opt_state, loss, aux

    @jax.jit
    def eval_step(state_trainable, state_frozen, source, target):
        return loss_fn(state_trainable, state_frozen, source, target)

    return train_step, eval_step


def shard_batch(batch: Dict[str, Any], mesh: Optional[Mesh]):
    """Device-put a host batch with its leading dim split over mesh 'dp'."""
    if mesh is None:
        return {k: jnp.asarray(v) for k, v in batch.items()}
    sharding = NamedSharding(mesh, P("dp"))
    out = {}
    for k, v in batch.items():
        arr = jnp.asarray(v)
        out[k] = jax.device_put(arr, sharding) if arr.ndim > 0 else arr
    return out


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Replicate train state across the mesh (params are small: ~0.2M)."""
    rep = NamedSharding(mesh, P())
    put = lambda t: jax.tree.map(lambda x: jax.device_put(x, rep), t)
    return TrainState(
        put(state.trainable), put(state.frozen), put(state.opt_state), state.step
    )
