"""Weak-supervision loss.

Parity target: train.py:110-156 of the reference. For a batch of positive
(matching) pairs, the per-direction softmax max-scores are averaged; negatives
are formed *in-batch* by rolling the source images by one (train.py:137), and
the loss is `mean_neg_score - mean_pos_score`.

TPU-first notes: the roll is a jnp.roll on device (no host round-trip) and
both forward passes run under one jit so XLA can share the backbone compute
graph. The mean-of-max reductions fuse into the correlation pipeline.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def pair_match_score(corr4d, normalization: str = "softmax"):
    """Mean mutual match score of a filtered correlation tensor.

    Implements the score of train.py:123-134: normalize the corr tensor as a
    distribution over A positions (for each B position) and vice versa, take
    the per-position max, and average the two directions.
    """
    b = corr4d.shape[0]
    fs1, fs2, fs3, fs4 = corr4d.shape[2:]
    nc_b_avec = corr4d.reshape(b, fs1 * fs2, fs3, fs4)
    nc_a_bvec = corr4d.reshape(b, fs1, fs2, fs3 * fs4)

    if normalization == "softmax":
        nc_b_avec = jax.nn.softmax(nc_b_avec, axis=1)
        nc_a_bvec = jax.nn.softmax(nc_a_bvec, axis=3)
    elif normalization == "l1":
        nc_b_avec = nc_b_avec / (jnp.sum(nc_b_avec, axis=1, keepdims=True) + 1e-4)
        nc_a_bvec = nc_a_bvec / (jnp.sum(nc_a_bvec, axis=3, keepdims=True) + 1e-4)
    elif normalization is not None:
        raise ValueError(f"unknown normalization {normalization!r}")

    scores_b = jnp.max(nc_b_avec, axis=1)  # [b, fs3, fs4]
    scores_a = jnp.max(nc_a_bvec, axis=3)  # [b, fs1, fs2]
    return (jnp.mean(scores_a) + jnp.mean(scores_b)) / 2


def weak_loss(forward_fn, source_image, target_image, normalization: str = "softmax"):
    """Positive-vs-rolled-negative weak loss (image-level entry).

    Args:
      forward_fn: (src, tgt) -> corr4d (the model forward closed over params).
      source_image, target_image: [b, 3, h, w].

    Returns:
      scalar loss = score(negatives) - score(positives).
    """
    corr_pos = forward_fn(source_image, target_image)
    score_pos = pair_match_score(corr_pos, normalization)

    # In-batch negatives: source rolled by one pairs each target with a
    # different image (parity: np.roll(np.arange(b), -1) at train.py:137).
    rolled = jnp.roll(source_image, -1, axis=0)
    corr_neg = forward_fn(rolled, target_image)
    score_neg = pair_match_score(corr_neg, normalization)

    return score_neg - score_pos


def weak_loss_from_features(match_fn, feat_a, feat_b,
                            normalization: str = "softmax",
                            remat_policy=None):
    """Weak loss entered after feature extraction — half the backbone FLOPs.

    The backbone is per-image (and its BN runs in inference mode,
    lib/model.py:251), so features of the rolled batch are exactly the
    rolled features: the negative pass can skip the backbone entirely.
    The reference runs two full forwards per step (train.py:121,138); here
    the backbone runs once and only the correlation pipeline runs twice.

    Args:
      match_fn: (feat_a, feat_b) -> corr4d (correlation pipeline closed over
        params, e.g. ncnet_forward_from_features).
      feat_a, feat_b: [b, c, h, w] backbone features.
      remat_policy: caller default for the checkpoint policy below; the
        NCNET_TRAIN_REMAT_POLICY env var still overrides (sweep knob).
        None falls back to "dots" — the v5e-measured winner.
    """
    import jax

    # Checkpoint each direction's pipeline-to-score: without it the
    # positive AND negative passes hold their full consensus activation
    # chains simultaneously for the backward (two symmetric Conv4d stacks
    # each) — several GB of the jit(train_step) HBM peak at the reference
    # schedule on a 16 GB chip. With it, each direction's residual is its
    # feature inputs and the backward recomputes one direction at a time.
    def direction_score(fa, fb):
        return pair_match_score(match_fn(fa, fb), normalization)

    # NCNET_TRAIN_REMAT_POLICY (trace time) tunes the memory/recompute
    # trade of this checkpoint — the round-2 campaign made the train step
    # FIT (20 GB) but left it recompute-heavy. Hardware sweep (v5e,
    # 2026-08-02 session_0257, reference schedule batch 16, 400 px):
    #   "full"  45.9 s/step — save nothing, recompute each direction;
    #   "dots"   5.4 s/step — save MXU contraction results
    #            (jax.checkpoint_policies.checkpoint_dots); the batch-16
    #            winner, promoted to the default;
    #   "none"  fails to compile at batch 16 (no-remat AD exceeds HBM)
    #           but WINS under --grad_accum 4 (4.5 vs 5.4 s/step: one
    #           micro-batch of activations fits) — make_train_step
    #           passes it as the caller default on the accum path.
    policy = os.environ.get(
        "NCNET_TRAIN_REMAT_POLICY", remat_policy or "dots"
    )
    if policy == "none":
        pass
    elif policy == "dots":
        direction_score = jax.checkpoint(
            direction_score, policy=jax.checkpoint_policies.checkpoint_dots
        )
    else:
        direction_score = jax.checkpoint(direction_score)
    score_pos = direction_score(feat_a, feat_b)
    # Under a dp-sharded batch the roll lowers to a collective permute of
    # the (small) feature tensors over ICI.
    score_neg = direction_score(jnp.roll(feat_a, -1, axis=0), feat_b)
    return score_neg - score_pos
