"""Training: weak-supervision loss, train state/steps, checkpointing."""

from .loss import weak_loss, pair_match_score
from .trainer import (
    TrainState,
    create_train_state,
    make_train_step,
    shard_batch,
    replicate_state,
)
from .checkpoint import (
    save_checkpoint,
    load_checkpoint,
    load_latest_checkpoint,
    load_opt_state,
    config_from_dict,
    resolve_resume_dir,
)

__all__ = [
    "weak_loss",
    "pair_match_score",
    "TrainState",
    "create_train_state",
    "make_train_step",
    "shard_batch",
    "replicate_state",
    "save_checkpoint",
    "load_checkpoint",
    "load_latest_checkpoint",
    "load_opt_state",
    "config_from_dict",
    "resolve_resume_dir",
]
