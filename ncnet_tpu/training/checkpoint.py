"""Checkpointing: self-describing save/restore with config-in-checkpoint.

Reference parity (lib/torch_util.py:48-61, train.py:198-206, and the restore
path lib/model.py:211-248): every epoch is saved, the best validation loss
copies to `best/`, and the architecture hyper-parameters travel *with* the
checkpoint and override caller args on restore (lib/model.py:217-220 — kept,
because it is what makes published checkpoints self-describing). Unlike the
reference, optimizer state is actually restored (the reference saves it but
never loads it — SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..models.backbone import BackboneConfig
from ..models.ncnet import NCNetConfig


def _config_to_dict(config: NCNetConfig) -> dict:
    d = dataclasses.asdict(config)
    return d


def config_from_dict(d: dict) -> NCNetConfig:
    bb = d.pop("backbone", {})
    d = dict(d)
    for key in ("ncons_kernel_sizes", "ncons_channels"):
        if key in d:
            d[key] = tuple(d[key])
    return NCNetConfig(backbone=BackboneConfig(**bb), **d)


def _save_tree(tree, path: str):
    """Flatten a pytree to an npz with path-encoded keys."""
    flat = {}

    def visit(prefix, node):
        if isinstance(node, dict):
            if not node:  # parameterless entries (e.g. pool layers)
                flat[f"{prefix}/__empty__"] = np.zeros(())
            for k, v in node.items():
                visit(f"{prefix}/{k}", v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                visit(f"{prefix}/#{i}", v)
        else:
            flat[prefix] = np.asarray(node)

    visit("", tree)
    np.savez(path, **flat)


def _load_tree(path: str):
    """Inverse of _save_tree."""
    data = np.load(path)
    root: Dict[str, Any] = {}
    for key in data.files:
        parts = [p for p in key.split("/") if p]
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = data[key]

    def listify(node):
        if isinstance(node, dict):
            if "__empty__" in node and len(node) == 1:
                return {}
            if node and all(k.startswith("#") for k in node):
                return [listify(node[f"#{i}"]) for i in range(len(node))]
            return {k: listify(v) for k, v in node.items()}
        return node

    return listify(root)


def save_checkpoint(
    directory: str,
    params: Dict[str, Any],
    config: NCNetConfig,
    epoch: int,
    opt_state=None,
    extra: Optional[dict] = None,
    is_best: bool = False,
    tag: Optional[str] = None,
):
    """Write params + config (+ opt state, metrics) under `directory/epoch_N`.

    `tag` overrides the directory name — the mid-epoch preemption
    checkpoints use the rolling tag "step" (written fresh to "step.tmp"
    and swapped in, so a kill mid-write leaves the previous complete
    "step" dir or a complete "step.tmp"; cli/train.py's resume checks
    both)."""
    os.makedirs(directory, exist_ok=True)
    rolling = tag is not None
    final_tag = os.path.join(directory, tag if rolling else f"epoch_{epoch}")
    tag = final_tag + ".tmp" if rolling else final_tag
    if rolling and os.path.exists(tag):
        shutil.rmtree(tag)
    os.makedirs(tag, exist_ok=True)
    _save_tree(jax.tree.map(np.asarray, params), os.path.join(tag, "params.npz"))
    if opt_state is not None:
        flat, treedef = jax.tree.flatten(opt_state)
        np.savez(
            os.path.join(tag, "opt_state.npz"),
            **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(flat)},
        )
        with open(os.path.join(tag, "opt_treedef.txt"), "w") as f:
            f.write(str(treedef))
    meta = {"config": _config_to_dict(config), "epoch": epoch, **(extra or {})}
    with open(os.path.join(tag, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=float)
    if rolling:
        if os.path.exists(final_tag):
            shutil.rmtree(final_tag)
        os.replace(tag, final_tag)
        tag = final_tag
    if is_best:
        best = os.path.join(directory, "best")
        if os.path.exists(best):
            shutil.rmtree(best)
        shutil.copytree(tag, best)
    return tag


def load_checkpoint(path: str, opt_state_template=None):
    """Load (params, config, meta[, opt_state]) from a checkpoint dir.

    The stored config wins over caller-supplied architecture args, matching
    the reference restore behavior (lib/model.py:217-220).
    """
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    config = config_from_dict(meta["config"])
    params = _load_tree(os.path.join(path, "params.npz"))
    result = {"params": params, "config": config, "meta": meta}
    if opt_state_template is not None:
        opt_state = load_opt_state(path, opt_state_template)
        if opt_state is not None:
            result["opt_state"] = opt_state
    return result


def load_opt_state(path: str, template):
    """Restore just the optimizer state from a checkpoint dir, or None.

    `template` supplies the pytree structure (opt states hold Python
    containers npz cannot describe). A leaf-count mismatch means the saved
    run used a different optimizer configuration — surfaced as a clear
    error rather than a cryptic unflatten failure.
    """
    opt_path = os.path.join(path, "opt_state.npz")
    if not os.path.exists(opt_path):
        return None
    data = np.load(opt_path)
    leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
    flat, treedef = jax.tree.flatten(template)
    if len(leaves) != len(flat):
        raise ValueError(
            f"optimizer state in {path!r} has {len(leaves)} leaves but the "
            f"current optimizer expects {len(flat)} — the checkpoint was "
            "saved with a different optimizer configuration (e.g. a "
            "different --fe_finetune_params); drop the stale opt_state.npz "
            "or match the original flags to resume it"
        )
    return jax.tree.unflatten(treedef, leaves)
