"""Checkpointing: self-describing save/restore with config-in-checkpoint.

Reference parity (lib/torch_util.py:48-61, train.py:198-206, and the restore
path lib/model.py:211-248): every epoch is saved, the best validation loss
copies to `best/`, and the architecture hyper-parameters travel *with* the
checkpoint and override caller args on restore (lib/model.py:217-220 — kept,
because it is what makes published checkpoints self-describing). Unlike the
reference, optimizer state is actually restored (the reference saves it but
never loads it — SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..models.backbone import BackboneConfig
from ..models.ncnet import NCNetConfig
from ..obs import train_watch
from ..reliability import failpoints


def _config_to_dict(config: NCNetConfig) -> dict:
    d = dataclasses.asdict(config)
    return d


def config_from_dict(d: dict) -> NCNetConfig:
    bb = d.pop("backbone", {})
    d = dict(d)
    for key in ("ncons_kernel_sizes", "ncons_channels"):
        if key in d:
            d[key] = tuple(d[key])
    return NCNetConfig(backbone=BackboneConfig(**bb), **d)


def _save_tree(tree, path: str):
    """Flatten a pytree to an npz with path-encoded keys."""
    flat = {}

    def visit(prefix, node):
        if isinstance(node, dict):
            if not node:  # parameterless entries (e.g. pool layers)
                flat[f"{prefix}/__empty__"] = np.zeros(())
            for k, v in node.items():
                visit(f"{prefix}/{k}", v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                visit(f"{prefix}/#{i}", v)
        else:
            flat[prefix] = np.asarray(node)

    visit("", tree)
    np.savez(path, **flat)


def _load_tree(path: str):
    """Inverse of _save_tree."""
    data = np.load(path)
    root: Dict[str, Any] = {}
    for key in data.files:
        parts = [p for p in key.split("/") if p]
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = data[key]

    def listify(node):
        if isinstance(node, dict):
            if "__empty__" in node and len(node) == 1:
                return {}
            if node and all(k.startswith("#") for k in node):
                return [listify(node[f"#{i}"]) for i in range(len(node))]
            return {k: listify(v) for k, v in node.items()}
        return node

    return listify(root)


def _rmtree_unmarked(path: str) -> None:
    """Remove a checkpoint dir, un-marking it complete FIRST.

    rmtree deletes entries in directory order, so a kill mid-rmtree
    could leave a gutted dir whose surviving meta.json still marks it
    complete to resolve_resume_dir; unlinking meta.json first makes the
    deletion safe at every kill point."""
    if not os.path.exists(path):
        return
    meta = os.path.join(path, "meta.json")
    if os.path.exists(meta):
        os.unlink(meta)
    shutil.rmtree(path)


def _swap_aside(tmp: str, final: str) -> None:
    """Promote a complete `tmp` dir to `final` via rename-aside.

    (final -> final.old; tmp -> final; rm final.old.) Every step is a
    rename or an un-marked delete, so a kill at ANY point leaves a
    complete dir at one of final / final.tmp / final.old — the triple
    resolve_resume_dir searches."""
    aside = final + ".old"
    _rmtree_unmarked(aside)
    if os.path.exists(final):
        os.replace(final, aside)
    os.replace(tmp, final)
    _rmtree_unmarked(aside)


def _copytree_meta_last(src: str, dst: str) -> None:
    """Copy a checkpoint dir so meta.json lands LAST, atomically.

    A plain copytree can copy the small meta.json before the bulky
    params.npz finishes, leaving a kill-window where a partial copy
    passes resolve_resume_dir's completeness check."""
    os.makedirs(dst)
    for entry in sorted(os.listdir(src)):
        if entry == "meta.json":
            continue
        s, d = os.path.join(src, entry), os.path.join(dst, entry)
        if os.path.isdir(s):
            shutil.copytree(s, d)
        else:
            shutil.copy2(s, d)
    meta_dst = os.path.join(dst, "meta.json")
    shutil.copy2(os.path.join(src, "meta.json"), meta_dst + ".tmp")
    os.replace(meta_dst + ".tmp", meta_dst)


def copy_checkpoint_dir(src: str, dst: str) -> None:
    """Kill-safe copy of a complete checkpoint dir to `dst`.

    Stale-.tmp guard, meta-last copy, rename-aside swap: a preemption at
    any point leaves either the previous complete `dst` (or a complete
    sibling resolve_resume_dir can find) — never a partial dir that
    passes the completeness check. Used for best/ promotion and the
    --resume best-carry."""
    _rmtree_unmarked(dst + ".tmp")
    _copytree_meta_last(src, dst + ".tmp")
    _swap_aside(dst + ".tmp", dst)


def save_checkpoint(
    directory: str,
    params: Dict[str, Any],
    config: NCNetConfig,
    epoch: int,
    opt_state=None,
    extra: Optional[dict] = None,
    is_best: bool = False,
    tag: Optional[str] = None,
):
    """Write params + config (+ opt state, metrics) under `directory/epoch_N`.

    `tag` overrides the directory name — the mid-epoch preemption
    checkpoints use the rolling tag "step", written fresh to "step.tmp"
    and swapped in rename-aside (step -> step.old; step.tmp -> step;
    rm step.old), so a kill at ANY point leaves at least one complete
    dir among step / step.tmp / step.old; `resolve_resume_dir` (used by
    cli/train.py --resume) checks all three in that order."""
    failpoints.fire("checkpoint.save", payload=directory)
    t_save = time.perf_counter()
    os.makedirs(directory, exist_ok=True)
    rolling = tag is not None
    final_tag = os.path.join(directory, tag if rolling else f"epoch_{epoch}")
    tag = final_tag + ".tmp" if rolling else final_tag
    if rolling:
        # A stale .tmp (earlier interrupted save) must not survive as a
        # "complete" sibling that outranks the fresh save.
        _rmtree_unmarked(tag)
    os.makedirs(tag, exist_ok=True)
    _save_tree(jax.tree.map(np.asarray, params), os.path.join(tag, "params.npz"))
    if opt_state is not None:
        flat, treedef = jax.tree.flatten(opt_state)
        np.savez(
            os.path.join(tag, "opt_state.npz"),
            **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(flat)},
        )
        with open(os.path.join(tag, "opt_treedef.txt"), "w") as f:
            f.write(str(treedef))
    meta = {"config": _config_to_dict(config), "epoch": epoch, **(extra or {})}
    # meta.json's presence is the completeness marker resolve_resume_dir
    # keys on, so it must APPEAR atomically: a kill mid-dump must not
    # leave a truncated meta.json that marks a partial dir complete.
    meta_path = os.path.join(tag, "meta.json")
    with open(meta_path + ".tmp", "w") as f:
        json.dump(meta, f, indent=2, default=float)
    os.replace(meta_path + ".tmp", meta_path)
    if rolling:
        # Fires between "new checkpoint fully written" and "swapped
        # live" — the kill-window the rename-aside dance exists for;
        # chaos tests inject here and assert resolve_resume_dir still
        # finds a complete dir.
        failpoints.fire("checkpoint.save.commit", payload=final_tag)
        # ADVICE r3: the old rmtree(final)-then-replace order had a
        # window where only a partial dir existed.
        _swap_aside(tag, final_tag)
        tag = final_tag
    if is_best:
        # Same discipline for best/: copy with meta landing last, then
        # rename-aside — a kill mid-copy leaves the previous complete
        # best/ (or a complete sibling) resolvable, never a partial dir
        # that passes the completeness check.
        copy_checkpoint_dir(tag, os.path.join(directory, "best"))
    # Checkpoint health telemetry (docs/OBSERVABILITY.md "Training
    # observatory"): save duration, bytes on disk, chain depth.
    train_watch.book_checkpoint_save(
        tag, directory, time.perf_counter() - t_save
    )
    return tag


def resolve_resume_dir(path: str) -> Optional[str]:
    """Resolve a --resume checkpoint dir, tolerating a rolling-swap kill.

    save_checkpoint's rename-aside swap guarantees a COMPLETE checkpoint
    always exists at one of `path`, `path + ".tmp"`, or `path + ".old"`
    no matter where a preemption lands; return the newest complete one
    (meta.json is written last, so its presence marks completeness), or
    None if none qualifies. `.tmp` is checked FIRST: a complete .tmp is
    always newer than `path` (each save rmtree's any stale .tmp before
    writing a fresh one), so preferring `path` would silently resume an
    older checkpoint and replay already-trained steps.
    """
    # A trailing slash (shell tab-completion) would turn `path + ".tmp"`
    # into a path INSIDE the dir instead of the sibling.
    path = os.path.normpath(path)
    for cand in (path + ".tmp", path, path + ".old"):
        # Completeness = meta.json (written last, atomically) AND
        # params.npz (belt-and-braces against a dir gutted by an
        # interrupted rmtree of a stale .tmp).
        if os.path.isfile(os.path.join(cand, "meta.json")) and os.path.isfile(
            os.path.join(cand, "params.npz")
        ):
            return cand
    return None


def load_checkpoint(path: str, opt_state_template=None):
    """Load (params, config, meta[, opt_state]) from a checkpoint dir.

    The stored config wins over caller-supplied architecture args, matching
    the reference restore behavior (lib/model.py:217-220).
    """
    failpoints.fire("checkpoint.load", payload=path)
    t_load = time.perf_counter()
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    config = config_from_dict(meta["config"])
    params = _load_tree(os.path.join(path, "params.npz"))
    result = {"params": params, "config": config, "meta": meta}
    if opt_state_template is not None:
        opt_state = load_opt_state(path, opt_state_template)
        if opt_state is not None:
            result["opt_state"] = opt_state
    train_watch.book_checkpoint_load(path, time.perf_counter() - t_load)
    return result


def checkpoint_candidates(directory: str) -> list:
    """COMPLETE checkpoint dirs under a run dir, newest first.

    Order: the rolling "step" family (``.tmp`` outranks ``step``
    outranks ``.old`` — the resolve_resume_dir rule), then ``epoch_N``
    descending. Completeness = meta.json AND params.npz present; a dir
    can still be torn *inside* a file (a truncated params.npz from a
    disk-full or a mid-write kill), which is what the fallback walk in
    :func:`load_latest_checkpoint` exists for.
    """
    out = []
    step = os.path.join(directory, "step")
    for cand in (step + ".tmp", step, step + ".old"):
        if os.path.isfile(os.path.join(cand, "meta.json")) and os.path.isfile(
            os.path.join(cand, "params.npz")
        ):
            out.append(cand)
    epochs = []
    try:
        entries = os.listdir(directory)
    except OSError:
        entries = []
    for entry in entries:
        if not entry.startswith("epoch_"):
            continue
        try:
            n = int(entry.split("_", 1)[1])
        except ValueError:
            continue
        cand = os.path.join(directory, entry)
        if os.path.isfile(os.path.join(cand, "meta.json")) and os.path.isfile(
            os.path.join(cand, "params.npz")
        ):
            epochs.append((n, cand))
    out.extend(cand for _n, cand in sorted(epochs, reverse=True))
    return out


def load_latest_checkpoint(directory: str, opt_state_template=None):
    """Load the newest LOADABLE checkpoint of a run dir, walking back.

    The elastic resume path (training/elastic.py) must not die because
    the newest checkpoint is torn (truncated params.npz, mangled
    meta.json): each failed candidate logs a ``checkpoint_fallback``
    event, bumps the ``train.checkpoint_fallbacks`` counter, and the
    walk continues to the next-newest complete dir. Returns
    ``(path, result)`` with ``result`` as :func:`load_checkpoint`'s
    dict; raises ``FileNotFoundError`` only when NO candidate loads.
    """
    from .. import obs

    errors = []
    for cand in checkpoint_candidates(directory):
        try:
            return cand, load_checkpoint(cand, opt_state_template)
        except Exception as exc:  # noqa: BLE001 — a torn file can
            # surface as BadZipFile/JSONDecodeError/OSError/KeyError
            # depending on which byte the truncation landed on; every
            # flavor means "walk back one checkpoint", none is fatal.
            errors.append((cand, exc))
            obs.counter("train.checkpoint_fallbacks").inc()
            obs.event(
                "checkpoint_fallback", path=cand,
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
    detail = "; ".join(
        f"{cand}: {type(exc).__name__}" for cand, exc in errors)
    raise FileNotFoundError(
        f"no loadable checkpoint under {directory!r}"
        + (f" (every candidate failed: {detail})" if detail else
           " (no complete candidate dirs)")
    )


def load_opt_state(path: str, template):
    """Restore just the optimizer state from a checkpoint dir, or None.

    `template` supplies the pytree structure (opt states hold Python
    containers npz cannot describe). A leaf-count mismatch means the saved
    run used a different optimizer configuration — surfaced as a clear
    error rather than a cryptic unflatten failure.
    """
    opt_path = os.path.join(path, "opt_state.npz")
    if not os.path.exists(opt_path):
        return None
    data = np.load(opt_path)
    leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
    flat, treedef = jax.tree.flatten(template)
    if len(leaves) != len(flat):
        raise ValueError(
            f"optimizer state in {path!r} has {len(leaves)} leaves but the "
            f"current optimizer expects {len(flat)} — the checkpoint was "
            "saved with a different optimizer configuration (e.g. a "
            "different --fe_finetune_params); drop the stale opt_state.npz "
            "or match the original flags to resume it"
        )
    return jax.tree.unflatten(treedef, leaves)
