"""Data layer: datasets, normalization, image I/O, prefetching loader."""

from .datasets import (
    ImagePairDataset,
    PFPascalDataset,
    PFWillowDataset,
    TSSDataset,
)
from .loader import DataLoader, default_collate
from .normalization import normalize_image, normalize_image_dict
from .image_io import read_image, resize_bilinear_np, load_and_resize_chw

__all__ = [
    "ImagePairDataset",
    "PFPascalDataset",
    "PFWillowDataset",
    "TSSDataset",
    "DataLoader",
    "default_collate",
    "normalize_image",
    "normalize_image_dict",
    "read_image",
    "resize_bilinear_np",
    "load_and_resize_chw",
]
