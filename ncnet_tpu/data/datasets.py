"""CSV-driven pair datasets: training pairs, PF-Pascal, PF-Willow, TSS.

Host-side numpy datasets with `__len__` / `__getitem__` returning dicts of
numpy arrays, consumed by `ncnet_tpu.data.loader`.

Reference parity:
  * ImagePairDataset  — lib/im_pair_dataset.py:11-93 (train/val pairs with
    class + flip columns; both images resized to a square output).
  * PFPascalDataset   — lib/pf_dataset.py:11-112 incl. the 'pf' and 'scnet'
    L_pck procedures; keypoints padded to 20 with -1.
  * PFWillowDataset   — lib/pf_willow_dataset.py:12-89 (10 points, L_pck from
    the target keypoints' bbox max side).
  * TSSDataset        — lib/tss_dataset.py:12-110 (pairs with flow direction
    and flip; returns the GT-flow relative path for output naming).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np
import pandas as pd

from .image_io import load_and_resize_chw
from .normalization import normalize_image_dict

MAX_KEYPOINTS = 20


class ImagePairDataset:
    """Weak-supervision training pairs (CSV: source, target, class, flip)."""

    def __init__(
        self,
        csv_path: str,
        image_path: str,
        output_size=(400, 400),
        normalize: bool = True,
        dataset_size: int = 0,
        random_crop: bool = False,
        rng: Optional[np.random.RandomState] = None,
    ):
        data = pd.read_csv(csv_path)
        if dataset_size:
            data = data.iloc[: min(dataset_size, len(data))]
        self.img_a = data.iloc[:, 0].tolist()
        self.img_b = data.iloc[:, 1].tolist()
        self.category = data.iloc[:, 2].to_numpy()
        self.flip = data.iloc[:, 3].to_numpy().astype(int)
        self.image_path = image_path
        self.out_h, self.out_w = output_size
        self.normalize = normalize
        self.random_crop = random_crop
        self.rng = rng or np.random.RandomState(0)

    def __len__(self):
        return len(self.img_a)

    def _load(self, rel, flip):
        path = os.path.join(self.image_path, rel)
        if self.random_crop:
            from .image_io import read_image, resize_bilinear_np

            img = read_image(path)
            h, w = img.shape[:2]
            top = self.rng.randint(h // 4 or 1)
            bottom = int(3 * h / 4 + self.rng.randint(h // 4 or 1))
            left = self.rng.randint(w // 4 or 1)
            right = int(3 * w / 4 + self.rng.randint(w // 4 or 1))
            img = img[top:bottom, left:right]
            im_size = np.asarray(img.shape, np.float32)
            if flip:
                img = img[:, ::-1]
            img = resize_bilinear_np(img, self.out_h, self.out_w)
            return img.transpose(2, 0, 1).copy(), im_size
        return load_and_resize_chw(path, self.out_h, self.out_w, flip=bool(flip))

    def __getitem__(self, idx):
        flip = self.flip[idx]
        image_a, size_a = self._load(self.img_a[idx], flip)
        image_b, size_b = self._load(self.img_b[idx], flip)
        sample = {
            "source_image": image_a,
            "target_image": image_b,
            "source_im_size": size_a,
            "target_im_size": size_b,
            "set": np.asarray(self.category[idx], np.float32),
        }
        if self.normalize:
            sample = normalize_image_dict(sample, ["source_image", "target_image"])
        return sample


def _parse_points(xs: str, ys: str, pad_to: int = MAX_KEYPOINTS) -> np.ndarray:
    """Parse ';'-separated coord lists, pad to fixed length with -1."""
    x = np.fromstring(xs, sep=";") if ";" in xs or xs else np.array([])
    y = np.fromstring(ys, sep=";") if ";" in ys or ys else np.array([])
    xp = -np.ones(pad_to)
    yp = -np.ones(pad_to)
    xp[: len(x)] = x
    yp[: len(x)] = y
    return np.stack([xp, yp]).astype(np.float32)


class PFPascalDataset:
    """PF-Pascal keypoint-transfer eval pairs."""

    def __init__(
        self,
        csv_path: str,
        dataset_path: str,
        output_size=(400, 400),
        category: Optional[int] = None,
        pck_procedure: str = "pf",
        normalize: bool = True,
    ):
        pairs = pd.read_csv(csv_path)
        self.category = pairs.iloc[:, 2].to_numpy().astype(float)
        if category is not None:
            keep = np.nonzero(self.category == category)[0]
            pairs = pairs.iloc[keep]
            self.category = self.category[keep]
        self.img_a = pairs.iloc[:, 0].tolist()
        self.img_b = pairs.iloc[:, 1].tolist()
        self.points_a = pairs.iloc[:, 3:5]
        self.points_b = pairs.iloc[:, 5:7]
        self.dataset_path = dataset_path
        self.out_h, self.out_w = output_size
        self.pck_procedure = pck_procedure
        self.normalize = normalize

    def __len__(self):
        return len(self.img_a)

    def __getitem__(self, idx):
        image_a, size_a = load_and_resize_chw(
            os.path.join(self.dataset_path, self.img_a[idx]), self.out_h, self.out_w
        )
        image_b, size_b = load_and_resize_chw(
            os.path.join(self.dataset_path, self.img_b[idx]), self.out_h, self.out_w
        )
        pts_a = _parse_points(self.points_a.iloc[idx, 0], self.points_a.iloc[idx, 1])
        pts_b = _parse_points(self.points_b.iloc[idx, 0], self.points_b.iloc[idx, 1])
        n_pts = int(np.sum(pts_a[0] != -1))

        if self.pck_procedure == "pf":
            l_pck = np.array(
                [np.max(pts_a[:, :n_pts].max(1) - pts_a[:, :n_pts].min(1))], np.float32
            )
        elif self.pck_procedure == "scnet":
            # SCNet procedure: rescale points (and nominal im size) to 224^2
            # (parity: lib/pf_dataset.py:64-75).
            pts_a[0, :n_pts] = pts_a[0, :n_pts] * 224 / size_a[1]
            pts_a[1, :n_pts] = pts_a[1, :n_pts] * 224 / size_a[0]
            pts_b[0, :n_pts] = pts_b[0, :n_pts] * 224 / size_b[1]
            pts_b[1, :n_pts] = pts_b[1, :n_pts] * 224 / size_b[0]
            size_a = size_a.copy()
            size_b = size_b.copy()
            size_a[0:2] = 224
            size_b[0:2] = 224
            l_pck = np.array([224.0], np.float32)
        else:
            raise ValueError(f"unknown pck procedure {self.pck_procedure!r}")

        sample = {
            "source_image": image_a,
            "target_image": image_b,
            "source_im_size": size_a,
            "target_im_size": size_b,
            "source_points": pts_a,
            "target_points": pts_b,
            "L_pck": l_pck,
        }
        if self.normalize:
            sample = normalize_image_dict(sample, ["source_image", "target_image"])
        return sample


class PFWillowDataset:
    """PF-Willow eval pairs (10 keypoints; L_pck = target-bbox max side)."""

    def __init__(self, csv_path, dataset_path, output_size=(400, 400), normalize=True):
        pairs = pd.read_csv(csv_path)
        self.img_a = pairs.iloc[:, 0].tolist()
        self.img_b = pairs.iloc[:, 1].tolist()
        self.points_a = pairs.iloc[:, 2:4]
        self.points_b = pairs.iloc[:, 4:6]
        self.dataset_path = dataset_path
        self.out_h, self.out_w = output_size
        self.normalize = normalize

    def __len__(self):
        return len(self.img_a)

    def __getitem__(self, idx):
        image_a, size_a = load_and_resize_chw(
            os.path.join(self.dataset_path, self.img_a[idx]), self.out_h, self.out_w
        )
        image_b, size_b = load_and_resize_chw(
            os.path.join(self.dataset_path, self.img_b[idx]), self.out_h, self.out_w
        )
        pts_a = _parse_points(self.points_a.iloc[idx, 0], self.points_a.iloc[idx, 1], 10)
        pts_b = _parse_points(self.points_b.iloc[idx, 0], self.points_b.iloc[idx, 1], 10)
        # L_pck from the SOURCE points bbox (parity: lib/pf_willow_dataset.py
        # uses point_A_coords max-min).
        l_pck = np.array([np.max(pts_a.max(1) - pts_a.min(1))], np.float32)
        sample = {
            "source_image": image_a,
            "target_image": image_b,
            "source_im_size": size_a,
            "target_im_size": size_b,
            "source_points": pts_a,
            "target_points": pts_b,
            "L_pck": l_pck,
        }
        if self.normalize:
            sample = normalize_image_dict(sample, ["source_image", "target_image"])
        return sample


class TSSDataset:
    """TSS dense-flow eval pairs (CSV: source, target, flow_direction, flip, category)."""

    def __init__(self, csv_path, dataset_path, output_size=(400, 400), normalize=True):
        data = pd.read_csv(csv_path)
        self.img_a = data.iloc[:, 0].tolist()
        self.img_b = data.iloc[:, 1].tolist()
        self.flow_direction = data.iloc[:, 2].to_numpy().astype(int)
        self.flip = data.iloc[:, 3].to_numpy().astype(int)
        self.dataset_path = dataset_path
        self.out_h, self.out_w = output_size
        self.normalize = normalize

    def __len__(self):
        return len(self.img_a)

    def __getitem__(self, idx):
        # Column 3 is flip_img_A: ONLY the source is mirrored
        # (tss_dataset.py:48-50 — image_B loads unflipped).
        flip = bool(self.flip[idx])
        image_a, size_a = load_and_resize_chw(
            os.path.join(self.dataset_path, self.img_a[idx]), self.out_h, self.out_w, flip
        )
        image_b, size_b = load_and_resize_chw(
            os.path.join(self.dataset_path, self.img_b[idx]), self.out_h, self.out_w, False
        )
        # GT flow lives next to the image pair; direction picks flow1/flow2.
        pair_dir = os.path.dirname(self.img_a[idx])
        flow_file = f"flow{self.flow_direction[idx]}.flo"
        sample = {
            "source_image": image_a,
            "target_image": image_b,
            "source_im_size": size_a,
            "target_im_size": size_b,
            "flow_path": os.path.join(pair_dir, flow_file),
        }
        if self.normalize:
            sample = normalize_image_dict(sample, ["source_image", "target_image"])
        return sample
