"""Image normalization (host-side numpy; parity: lib/normalization.py:5-50)."""

from __future__ import annotations

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize_image(image, forward: bool = True, mean=IMAGENET_MEAN, std=IMAGENET_STD):
    """Normalize (or de-normalize) a [..., 3, h, w] float image array.

    `forward=True`: (x - mean) / std. `forward=False` inverts. The /255 range
    normalization is the caller's responsibility (see `normalize_image_dict`).
    """
    mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
    std = np.asarray(std, np.float32).reshape(-1, 1, 1)
    if forward:
        return (image - mean) / std
    return image * std + mean


def normalize_image_dict(sample: dict, image_keys, normalize_range: bool = True) -> dict:
    """Normalize the named image entries of a sample dict in place-free style."""
    out = dict(sample)
    for key in image_keys:
        img = np.asarray(out[key], np.float32)
        if normalize_range:
            img = img / 255.0
        out[key] = normalize_image(img)
    return out
