"""Host-side batching data loader with background prefetch.

Replaces the reference's vendored PyTorch-0.3 DataLoader
(lib/dataloader.py:39-316, a multiprocessing fork-pool with an out-of-order
reordering dict). TPU input pipelines are host-bound but simpler: a
thread-pool maps `dataset[i]` (PIL decode + numpy resize release the GIL),
batches are collated into stacked numpy arrays, and a bounded prefetch queue
overlaps host decode with device steps.

The reference's one local modification — deterministic per-worker RNG seeding
(lib/dataloader.py:43,165) — becomes explicit: shuffling is driven by a
caller-provided seed, and any per-sample randomness lives in the dataset's
own RandomState.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np

from .. import obs


def default_collate(samples):
    """Stack a list of sample dicts into a batch dict.

    numpy arrays stack; scalars become [b] arrays; strings (e.g. flow paths)
    collect into lists — covering what lib/torch_util.py:9-24's
    collate_custom handled for ragged annotations.
    """
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        if isinstance(vals[0], np.ndarray):
            out[key] = np.stack(vals)
        elif isinstance(vals[0], (int, float, np.floating, np.integer)):
            out[key] = np.asarray(vals)
        else:
            out[key] = vals
    return out


class DataLoader:
    """Iterate a dataset in shuffled batches with threaded prefetch."""

    def __init__(
        self,
        dataset,
        batch_size: int = 16,
        shuffle: bool = False,
        num_workers: int = 4,
        seed: int = 1,
        drop_last: bool = False,
        prefetch: int = 2,
        collate_fn=default_collate,
        batch_slice: Optional[tuple] = None,
    ):
        """batch_slice=(start, stop): decode only those rows of every batch —
        the multi-host input pattern (each host runs the same deterministic
        index schedule, seeds being equal, and reads just its
        parallel.multihost.host_local_slice of each global batch)."""
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = max(num_workers, 1)
        self.seed = seed
        self.drop_last = drop_last
        self.prefetch = prefetch
        self.collate_fn = collate_fn
        if batch_slice is not None and not drop_last:
            # A ragged final batch would slice to unequal per-host row
            # counts and wedge the cross-host array assembly downstream.
            raise ValueError("batch_slice requires drop_last=True")
        self.batch_slice = batch_slice
        self._epoch = 0

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        """Position the shuffle schedule: the NEXT iteration shuffles
        with RandomState(seed + epoch) — mid-epoch training resume
        (cli/train.py --resume) replays an exact batch order."""
        self._epoch = epoch

    def _batch_indices(self):
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self._epoch)
            rng.shuffle(idx)
        batches = [
            idx[i : i + self.batch_size]
            for i in range(0, len(idx), self.batch_size)
        ]
        if self.drop_last and batches and len(batches[-1]) < self.batch_size:
            batches.pop()
        if self.batch_slice is not None:
            start, stop = self.batch_slice
            batches = [b[start:stop] for b in batches]
        return batches

    def __iter__(self) -> Iterator[dict]:
        batches = self._batch_indices()
        self._epoch += 1
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def put(item):
            """Bounded put that aborts when the consumer goes away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def produce():
            try:
                with ThreadPoolExecutor(self.num_workers) as pool:
                    for batch_idx in batches:
                        if stop.is_set():
                            return
                        samples = list(
                            pool.map(self.dataset.__getitem__, batch_idx)
                        )
                        batch = self.collate_fn(samples)
                        # Manifest identity rides along host-side: the
                        # training divergence sentinel's flight ring
                        # names the offending batch by dataset indices
                        # (obs/train_watch.py). Never device-put.
                        batch["_indices"] = np.asarray(batch_idx)
                        put(batch)
                put(None)
            except BaseException as exc:  # propagate to the consumer
                put(exc)

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        depth = obs.gauge("data.loader.queue_depth")
        starved = obs.counter("data.loader.starved")
        try:
            while True:
                # An empty queue at get() means the device side is about to
                # wait on host decode — the input-bound signal the run log
                # surfaces as data.loader.starved.
                depth.set(q.qsize())
                if q.empty():
                    starved.inc()
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()


def device_prefetch(iterator, put_fn, depth: int = 2):
    """Overlap host->device transfer with device compute.

    jax.device_put is asynchronous: enqueueing the NEXT batch's transfer
    before yielding the current one lets H2D copy ride under the train
    step. `put_fn` maps a host batch to device arrays (e.g.
    training.shard_batch); depth=2 keeps one batch in flight.
    """
    if depth < 1:
        raise ValueError("depth must be >= 1")
    from collections import deque

    pending = deque()
    for item in iterator:
        pending.append(put_fn(item))
        if len(pending) >= depth:
            yield pending.popleft()
    while pending:
        yield pending.popleft()
