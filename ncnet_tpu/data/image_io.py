"""Host-side image loading and resizing for the input pipeline.

The reference reads with skimage and resizes through an identity affine
grid-sample on the CPU torch path (lib/im_pair_dataset.py:59-93). Here images
are read with PIL and resized with a numpy corner-aligned bilinear resize that
matches `ncnet_tpu.geometry.grid.resize_bilinear` (same align_corners=True
semantics), so host preprocessing and on-device code agree.
"""

from __future__ import annotations

import numpy as np
from PIL import Image

from .. import obs
from ..reliability import failpoints
from ..reliability.failpoints import InjectedFault
from ..reliability.retry import RetryPolicy

#: Loader IO is retried briefly before surfacing: transient read errors
#: (NFS blip, racing writer) are routine at dataset scale, and one
#: failed sample otherwise fails its whole prefetch batch
#: (data/loader.py propagates per-batch). Injected faults retry too —
#: that is how the chaos tests exercise this path. Bounded tight: a
#: *permanently* corrupt file must fail fast, not stall an epoch.
_IO_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.02,
                        max_delay_s=0.25, deadline_s=2.0)


def read_image(path: str) -> np.ndarray:
    """Read an image as [h, w, 3] uint8 (grayscale broadcast to 3 channels).

    Non-8-bit inputs (e.g. 16-bit PNGs) are converted through PIL to 8-bit,
    matching the native loader's png_set_strip_16 behavior — both paths must
    produce the same value scale.
    """
    img = Image.open(path)
    arr = np.asarray(img)
    if arr.dtype != np.uint8:
        arr = np.asarray(img.convert("RGB"))
    if arr.ndim == 2:
        arr = np.repeat(arr[:, :, None], 3, axis=2)
    if arr.shape[2] == 4:
        arr = arr[:, :, :3]
    return arr


def resize_bilinear_np(image: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Corner-aligned bilinear resize of [h, w, c] float/uint8 -> float32."""
    h, w = image.shape[:2]
    img = image.astype(np.float32)
    ys = np.linspace(0, h - 1, out_h)
    xs = np.linspace(0, w - 1, out_w)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    out = (
        img[np.ix_(y0, x0)] * (1 - wy) * (1 - wx)
        + img[np.ix_(y0, x1)] * (1 - wy) * wx
        + img[np.ix_(y1, x0)] * wy * (1 - wx)
        + img[np.ix_(y1, x1)] * wy * wx
    )
    return out


def load_and_resize_chw(
    path: str, out_h: int, out_w: int, flip: bool = False, normalize: bool = False
) -> tuple:
    """Read, optionally h-flip, resize; return ([3,h,w] float32, orig (h,w,c)).

    With normalize=True the output is ImageNet-normalized ((x/255-mean)/std)
    instead of raw 0..255. Uses the native C++ decode+resize
    (ncnet_tpu/native/image_loader.cpp — identical corner-aligned arithmetic,
    GIL-free) when built; falls back to the PIL + numpy path for unsupported
    formats or a missing toolchain.

    Transient read errors are retried per ``_IO_RETRY`` before the
    terminal exception surfaces; the ``loader.read`` failpoint injects
    faults here (docs/RELIABILITY.md).
    """

    def _load():
        failpoints.fire("loader.read", payload=path)
        try:
            from ncnet_tpu import native

            if native.image_available():
                chw, (h, w) = native.load_image_chw_native(
                    path, out_h, out_w, flip=flip, normalize=normalize
                )
                return (failpoints.corrupt("loader.read", chw),
                        np.asarray((h, w, 3), np.float32))
        except (OSError, RuntimeError) as exc:
            # Native decode failed for THIS file; the PIL path below is
            # the fallback — but a silently-swallowed reason is how a
            # systemically broken native loader (bad .so, format bug)
            # hides as a 10x-slower epoch. Count and log every fallback.
            obs.counter("image_io.decode_errors").inc()
            obs.event("image_io_decode_error", path=path, stage="native",
                      error=f"{type(exc).__name__}: {exc}")
        img = read_image(path)
        im_size = np.asarray(img.shape, np.float32)
        if flip:
            img = img[:, ::-1]
        img = resize_bilinear_np(img, out_h, out_w).transpose(2, 0, 1)
        if normalize:
            from .normalization import normalize_image

            img = normalize_image(img / 255.0)
        chw = np.ascontiguousarray(img, dtype=np.float32)
        return failpoints.corrupt("loader.read", chw), im_size

    return _IO_RETRY.call(_load, retry_on=(OSError, InjectedFault),
                          site="loader.read")
