"""Spatially-sharded InLoc forward: multi-chip dense matching.

Composes the pieces of corr_sharding.py into the full high-resolution
matching step (SURVEY.md §3.3) with the correlation tensor sharded along
iA across the mesh — the multi-chip path for resolutions whose (even
pooled) correlation tensor plus workspace exceeds one chip's HBM:

    backbone (replicated)
      -> per-shard fused correlation + maxpool4d  (no communication:
         each shard owns a slab of A rows; pooling is local to a slab)
      -> mutual matching (pmax over shards)
      -> symmetric NeighConsensus (halo-exchange Conv4d; the transposed
         branch is the swapped-kernel chain — no all_to_all re-layout)
      -> mutual matching
    -> globally-shaped corr4d + relocalization deltas for corr_to_matches.

The reference has no distributed counterpart (single GPU, fp16+maxpool
as the only memory lever — eval_inloc.py:50, lib/model.py:269-272).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.ncnet import NCNetConfig, extract_features
from .corr_sharding import make_sharded_match_pipeline
from .mesh import shard_map_compat


def make_sharded_inloc_parts(config: NCNetConfig, mesh: Mesh, axis_name: str = "sp"):
    """Build the sharded InLoc forward, split for query-feature reuse.

    Returns (query_features, forward_from_features):
      query_features(params, image) -> feat: jitted replicated backbone —
        run once per query, its result feeds every shortlisted pano.
      forward_from_features(params, feat_a, tgt) -> (corr4d, delta4d):
        pano backbone + per-shard fused corr+pool + sharded consensus.
        delta4d is the kernel's packed int32 offset tensor (the
        models/ncnet.py fused-path contract); corr_to_matches consumes
        it directly.

    Requirements: batch 1; feature height iA divisible by
    (mesh size * relocalization_k_size) — the input bucketing in
    cli/eval_inloc.py pads images so this holds.
    """
    # Local import keeps jax.experimental.pallas off the import path of
    # consumers that never build the sharded InLoc forward (same policy as
    # models/ncnet.py's fused branch).
    from ..ops.pallas_kernels import fused_correlation_maxpool

    k = config.relocalization_k_size
    if k <= 1:
        raise ValueError("sharded InLoc forward requires relocalization_k_size > 1")
    spec_fa = P(None, None, axis_name, None)
    spec_corr = P(None, None, axis_name, None, None, None)

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(spec_fa, P()),
        out_specs=(spec_corr, spec_corr),
    )
    def corr_pool_local(fa_local, fb):
        # Each shard computes corr rows for its A slab and pools them —
        # embarrassingly parallel (pool cells never straddle shards since
        # I_loc is a multiple of k). The PACKED offsets are shard-position-
        # independent (they encode *within-cell* offsets), so per-shard
        # packed tensors concatenate into the global one directly — same
        # contract as the single-device fused path (models/ncnet.py).
        pooled, packed = fused_correlation_maxpool(
            fa_local, fb, k_size=k, corr_dtype=config.corr_dtype,
            decode_deltas=False,
        )
        return pooled, packed

    pipeline = make_sharded_match_pipeline(
        mesh, axis_name, symmetric=config.symmetric_mode
    )

    @jax.jit
    def query_features(params, image):
        return extract_features(config, params, image)

    n_shards = mesh.shape[axis_name]

    def _check_shapes(feat_a, feat_b):
        # Trace-time (shapes are static): a non-conforming iA would otherwise
        # surface as an opaque shard_map divisibility error — or worse,
        # silently truncate rows in the kernel's `ia // k` cell math.
        b, _, ia, ja = feat_a.shape
        ib, jb = feat_b.shape[2:]
        if b != 1:
            raise ValueError(f"sharded InLoc forward requires batch 1, got {b}")
        if ia % (n_shards * k):
            raise ValueError(
                f"feature height iA={ia} must be divisible by mesh size x "
                f"relocalization_k_size = {n_shards}*{k}={n_shards * k}; pad "
                "the input image so the feature height conforms "
                "(cli/eval_inloc.py's load_inloc_image(extra_align=mesh_size)"
                " buckets inputs this way)"
            )
        bad = {
            name: v for name, v in (("jA", ja), ("iB", ib), ("jB", jb)) if v % k
        }
        if bad:
            raise ValueError(
                f"feature dims {bad} must be divisible by "
                f"relocalization_k_size={k}"
            )

    @jax.jit
    def forward_from_features(params, feat_a, target_image):
        feat_b = extract_features(config, params, target_image)
        _check_shapes(feat_a, feat_b)
        feat_a = lax.with_sharding_constraint(
            feat_a, NamedSharding(mesh, spec_fa)
        )
        # Same dtype policy as models.ncnet.match_pipeline: corr_pool_local
        # already emits corr_dtype (bf16 under half_precision), the sharded
        # consensus keeps that storage dtype with f32 conv accumulation, and
        # the output is cast to f32 for extraction.
        pooled, deltas = corr_pool_local(feat_a, feat_b)
        corr4d = pipeline(params["neigh_consensus"], pooled)
        return corr4d.astype(jnp.float32), deltas

    return query_features, forward_from_features


def make_sharded_inloc_forward(config: NCNetConfig, mesh: Mesh, axis_name: str = "sp"):
    """Build a jitted (params, src, tgt) -> (corr4d, delta4d) forward.

    One-shot composition of `make_sharded_inloc_parts` (no feature reuse
    across calls); callers looping one query against many panos should use
    the parts directly.
    """
    query_features, forward_from_features = make_sharded_inloc_parts(
        config, mesh, axis_name
    )

    @jax.jit
    def forward(params, source_image, target_image):
        return forward_from_features(
            params, query_features(params, source_image), target_image
        )

    return forward
