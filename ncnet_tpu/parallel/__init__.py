"""Parallelism: mesh construction, DP sharding, corr-tensor spatial sharding."""

from . import multihost
from .mesh import make_mesh, batch_sharding, replicated, serving_devices
from .corr_sharding import (
    make_sharded_match_pipeline,
    sharded_correlation,
    match_pipeline_sharded,
    mutual_matching_sharded,
    neigh_consensus_sharded,
    conv4d_haloed,
)

__all__ = [
    "multihost",
    "make_sharded_inloc_forward",
    "make_sharded_inloc_parts",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "serving_devices",
    "make_sharded_match_pipeline",
    "sharded_correlation",
    "match_pipeline_sharded",
    "mutual_matching_sharded",
    "neigh_consensus_sharded",
    "conv4d_haloed",
]


def make_sharded_inloc_forward(*args, **kwargs):
    """Lazy re-export: importing it eagerly would pull jax.experimental.pallas
    onto the import path of every parallel-package consumer."""
    from .inloc_sharded import make_sharded_inloc_forward as fn

    return fn(*args, **kwargs)


def make_sharded_inloc_parts(*args, **kwargs):
    """Lazy re-export (see make_sharded_inloc_forward)."""
    from .inloc_sharded import make_sharded_inloc_parts as fn

    return fn(*args, **kwargs)
