"""Multi-host bootstrap: one mesh spanning all hosts' devices.

The reference is strictly single-process/single-GPU (SURVEY.md §2.8); here
multi-host scaling is the same mesh abstraction as single-host — the mesh
simply spans every host's devices, collectives ride ICI within a slice and
DCN across slices, and XLA handles the transport. This module owns the only
process-level coordination the framework needs: `jax.distributed.initialize`
plus helpers for host-local batch handling.

Typical use (same program on every host, e.g. under a TPU pod launcher):

    from ncnet_tpu.parallel import multihost
    multihost.initialize()                       # no-op single-host
    mesh = multihost.global_mesh(("dp",))        # all devices, all hosts
    start, stop = multihost.host_local_slice(global_batch_size)
    local_rows = {k: v[start:stop] for k, v in host_batch.items()}
    batch = multihost.host_local_batch(local_rows, mesh)  # global arrays
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the JAX distributed runtime (idempotent; single-host no-op).

    With no arguments, relies on the environment (TPU pod runtimes and the
    standard JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID
    variables); explicit arguments win. Safe to call unconditionally: when
    neither arguments nor environment indicate a multi-process run, it does
    nothing.
    """
    global _initialized
    if _initialized:
        return
    env = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if coordinator_address is None and not env:
        return  # single-host
    # JAX itself only auto-detects managed clusters (Slurm, OpenMPI, TPU
    # pods); the generic JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    # JAX_PROCESS_ID variables are this framework's convention and must be
    # passed through explicitly.
    def _env_int(*names):
        for name in names:
            v = os.environ.get(name)
            if v is not None:
                return int(v)
        return None

    jax.distributed.initialize(
        coordinator_address=coordinator_address or env,
        num_processes=(
            num_processes if num_processes is not None
            else _env_int("JAX_NUM_PROCESSES", "NUM_PROCESSES")
        ),
        process_id=(
            process_id if process_id is not None
            else _env_int("JAX_PROCESS_ID", "PROCESS_ID")
        ),
    )
    _initialized = True


def global_mesh(axis_names: Sequence[str] = ("dp",), shape: Tuple[int, ...] = ()) -> Mesh:
    """Mesh over ALL devices of ALL hosts (jax.devices() is global).

    Default: 1-D mesh over every device. Pass `shape` for multi-axis meshes
    (must multiply to the global device count).
    """
    import numpy as np

    devices = np.asarray(jax.devices())
    if not shape:
        shape = (devices.size,)
    return Mesh(devices.reshape(shape), axis_names)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def host_label() -> str:
    """Stable replica label for this host's training telemetry.

    Step beacons (obs/train_watch.py) publish ``train.step_index``
    under this label; ``obs.aggregate.merge_snapshots`` then treats it
    as the aggregation dimension, so per-host step positions survive a
    fleet merge and straggler lag is computable."""
    return f"host{jax.process_index()}"


def host_local_slice(
    global_batch_size: int,
    rank: Optional[int] = None,
    n_hosts: Optional[int] = None,
) -> Tuple[int, int]:
    """[start, stop) of this host's rows of a globally-sharded batch.

    The data loader on each host reads only its slice; `host_local_batch`
    then assembles the global arrays without cross-host transfer (the
    standard multi-host input pattern).

    `rank` / `n_hosts` default to the JAX process grid; the elastic
    driver (training/elastic.py) passes its membership-derived values
    instead, so the slice tracks the LIVE generation rather than the
    process set the run was launched with.
    """
    n = jax.process_count() if n_hosts is None else int(n_hosts)
    i = jax.process_index() if rank is None else int(rank)
    if n < 1:
        raise ValueError(f"host count must be >= 1, got {n}")
    if not 0 <= i < n:
        raise ValueError(f"host rank {i} out of range for {n} hosts")
    if global_batch_size % n:
        raise ValueError(
            f"global batch {global_batch_size} is not divisible by the "
            f"{n} hosts sharding it (remainder {global_batch_size % n}): "
            "every host must decode the same row count or the cross-host "
            "array assembly wedges; pick a multiple of the host count, or "
            "let the elastic driver round it down "
            "(training/elastic.py adjusted_global_batch)"
        )
    per = global_batch_size // n
    return i * per, (i + 1) * per


def host_local_batch(batch: dict, mesh: Mesh, axis: str = "dp") -> dict:
    """Assemble global batch-sharded arrays from each host's local rows.

    `batch` maps names to THIS host's rows (its `host_local_slice` of the
    global batch). jax.make_array_from_process_local_data places local rows
    on local devices — no data crosses DCN. Works unchanged single-host,
    where it is equivalent to a sharded device_put.
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in batch.items()
    }
