"""Filesystem-rendezvous membership for elastic multi-host training.

ROADMAP item 4's training half: one preempted host must not kill the
run. There is no etcd on a TPU pod, but there IS a shared filesystem
(the checkpoint chain already rides it), so membership is a
filesystem-rendezvous plane with the same durability discipline as the
bulk pipeline's ledger (pipeline/bulk.py):

* **Leases** — every live host renews ``<root>/hosts/<host>.lease.json``
  (atomic tmp + fsync + ``os.replace``; a torn lease is unreadable, not
  wrong). A lease older than ``lease_ttl_s`` is an expired host. Each
  lease carries an ``owner`` nonce, so a second process heartbeating
  the same host name is detected as a steal instead of two processes
  silently sharing one identity.

* **Generation record** — ``<root>/generation.json`` is the single
  source of truth for *who is in the run*: a monotonic ``generation``
  counter plus the ordered live-host list and the resume marker
  (epoch/step of the last committed checkpoint at bump time). It is
  only ever mutated under an exclusive ``flock`` of
  ``<root>/.membership.lock`` and only ever moves FORWARD: a bump that
  would not raise the generation returns the newer record instead of
  writing (two survivors racing the same eviction converge on one
  bump). Rank within a generation is position in the sorted host list,
  so every member derives the same ``host_local_slice`` without
  another round of coordination.

* **Rejoin** — a host that lost its lease and comes back observes a
  generation that no longer lists it; ``join``/``renew`` raise
  :class:`StaleGenerationError` instead of letting it write state at
  the old generation. Re-admission is an explicit ``bump`` (grow) that
  the survivors pick up exactly like a shrink.

Failpoints (docs/RELIABILITY.md "Planted sites"): ``membership.lease``
fires on every lease write (kill = a host dying mid-heartbeat;
delay = a slow NFS renew), ``membership.detect`` fires on every
dead-host scan (error = a detector crash drill).

The clock is injectable (``clock=``) so lease expiry, steal and bump
ordering are unit-testable without wall-time sleeps
(tests/test_membership.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..reliability import failpoints


class MembershipError(RuntimeError):
    """Base class for membership-plane failures."""


class StaleGenerationError(MembershipError):
    """This host acted at a generation the plane has moved past —
    it was evicted (lease expired, survivors bumped) and must re-enter
    through the CURRENT generation instead of writing old state."""

    def __init__(self, host: str, held: int, record: dict):
        super().__init__(
            f"host {host!r} holds generation {held} but the membership "
            f"plane is at generation {record.get('generation')} with hosts "
            f"{record.get('hosts')} — rejoin via a new bump, do not write "
            "state at the old generation"
        )
        self.host = host
        self.held = held
        self.record = record


class LeaseStolenError(MembershipError):
    """Another process wrote this host's lease: two processes are
    heartbeating the same host identity (a relaunch raced the
    original). The loser must stop writing immediately."""

    def __init__(self, host: str, owner: str, found: str):
        super().__init__(
            f"lease for host {host!r} is owned by {found!r}, not {owner!r} "
            "— a second process claimed this host identity"
        )
        self.host = host


def _write_json_atomic(path: str, rec: dict) -> None:
    """tmp + fsync + rename (+ best-effort dir fsync): readers see the
    old record or the new one, never a torn one — the bulk-ledger
    checkpoint discipline (pipeline/bulk.py)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(rec, fh, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def _read_json(path: str) -> Optional[dict]:
    """A missing or torn file reads as None (a crash mid-write leaves
    only the previous complete record or nothing)."""
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


class MembershipPlane:
    """One host's handle on the lease + generation files under ``root``.

    Single-threaded per instance EXCEPT for lease writes: ``renew`` is
    called from both the training thread (inline checks) and the
    :class:`LeaseHeartbeat` thread, and is safe because each call
    re-reads shared files and the write itself is an atomic rename.
    """

    def __init__(self, root: str, host: str, lease_ttl_s: float = 5.0,
                 clock: Callable[[], float] = time.time):
        if not host:
            raise ValueError("membership host id must be non-empty")
        self.root = root
        self.host = host
        self.lease_ttl_s = float(lease_ttl_s)
        self._clock = clock
        # Owner nonce: distinguishes "my own earlier write" from a
        # second process claiming the same host name.
        self._owner = f"{os.getpid()}.{os.urandom(4).hex()}"
        os.makedirs(os.path.join(root, "hosts"), exist_ok=True)

    # -- paths ------------------------------------------------------------

    def _lease_path(self, host: str) -> str:
        return os.path.join(self.root, "hosts", f"{host}.lease.json")

    @property
    def generation_path(self) -> str:
        return os.path.join(self.root, "generation.json")

    def _locked(self):
        """Exclusive flock over the generation record (blocking: bumps
        are rare and fast). Returns the open fh; closing drops it."""
        fh = open(os.path.join(self.root, ".membership.lock"), "a+")
        try:
            import fcntl

            fcntl.flock(fh, fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: single-process tests only
            pass
        return fh

    # -- generation record ------------------------------------------------

    def read_generation(self) -> Optional[dict]:
        return _read_json(self.generation_path)

    def form(self, hosts: Sequence[str],
             resume_epoch: int = 1, resume_step: int = 0) -> dict:
        """Create the generation-1 record from the declared host list
        (idempotent: every host of the gang calls this at launch; the
        first writer wins, the rest adopt the existing record)."""
        if self.host not in hosts:
            raise ValueError(
                f"forming host {self.host!r} is not in the declared host "
                f"list {list(hosts)}"
            )
        fh = self._locked()
        try:
            existing = self.read_generation()
            if existing is not None:
                return existing
            rec = {
                "generation": 1,
                "hosts": sorted(hosts),
                "resume_epoch": int(resume_epoch),
                "resume_step": int(resume_step),
                "t": self._clock(),
            }
            _write_json_atomic(self.generation_path, rec)
            return rec
        finally:
            fh.close()

    def bump(self, hosts: Sequence[str], resume_epoch: int,
             resume_step: int, expected_generation: int) -> dict:
        """Advance the generation to a new host list (shrink OR grow).

        Monotonic and idempotent under races: if the record already
        moved past ``expected_generation`` (another survivor bumped
        first), the NEWER record is returned unwritten — callers treat
        the return value, not their argument, as the outcome.
        """
        fh = self._locked()
        try:
            cur = self.read_generation()
            if cur is None:
                raise MembershipError(
                    f"no generation record at {self.generation_path} "
                    "(form() was never called)"
                )
            if cur["generation"] > expected_generation:
                return cur
            rec = {
                "generation": int(cur["generation"]) + 1,
                "hosts": sorted(hosts),
                "resume_epoch": int(resume_epoch),
                "resume_step": int(resume_step),
                "t": self._clock(),
            }
            _write_json_atomic(self.generation_path, rec)
            return rec
        finally:
            fh.close()

    # -- leases -----------------------------------------------------------

    def join(self, generation: Optional[int] = None, step: int = 0,
             epoch: int = 0) -> dict:
        """Write this host's first lease at the current generation.

        A host not listed in the current generation (it died, the
        survivors moved on) is REJECTED here — re-entry happens through
        an explicit ``bump``, never by writing at the old generation.
        """
        rec = self.read_generation()
        if rec is None:
            raise MembershipError(
                f"no generation record at {self.generation_path} "
                "(form() was never called)"
            )
        if self.host not in rec["hosts"]:
            raise StaleGenerationError(
                self.host, generation if generation is not None
                else rec["generation"] - 1, rec)
        self._write_lease(rec["generation"], step, epoch)
        return rec

    def renew(self, generation: int, step: int = 0, epoch: int = 0) -> None:
        """Renew this host's lease; the heartbeat path.

        Raises :class:`StaleGenerationError` when the current record no
        longer LISTS this host (it was evicted; survivors moved on) and
        :class:`LeaseStolenError` when another process owns the lease.
        A record that moved ahead while still listing this host is NOT
        an error — that is the normal window between a peer's bump and
        this host's next generation read (the lease stays fresh so the
        peer does not evict a live host mid-transition).
        """
        failpoints.fire("membership.lease", payload=self.host)
        rec = self.read_generation()
        if rec is not None and self.host not in rec["hosts"]:
            raise StaleGenerationError(self.host, generation, rec)
        lease = _read_json(self._lease_path(self.host))
        if lease is not None and lease.get("owner") != self._owner:
            raise LeaseStolenError(
                self.host, self._owner, str(lease.get("owner")))
        self._write_lease(generation, step, epoch)

    def _write_lease(self, generation: int, step: int,
                     epoch: int = 0) -> None:
        # (epoch, step) is this host's advertised training position:
        # peers use it as the commit barrier (a checkpoint may only
        # commit a position every live member has reached — the
        # stand-in for "the collective completed this step", without
        # which survivors could commit past a dead host's last
        # contribution). A stale lease UNDERSTATES progress, so the
        # barrier errs toward later commits, never unsafe ones.
        _write_json_atomic(self._lease_path(self.host), {
            "host": self.host,
            "owner": self._owner,
            "pid": os.getpid(),
            "generation": int(generation),
            "epoch": int(epoch),
            "step": int(step),
            "t": self._clock(),
        })

    def drop_lease(self) -> None:
        """Remove this host's lease (clean shutdown: peers see an
        orderly departure at the next scan instead of waiting a TTL)."""
        try:
            os.unlink(self._lease_path(self.host))
        except OSError:
            pass

    def live_view(self) -> Dict[str, dict]:
        """Every readable lease, keyed by host (expired ones included —
        callers judge freshness against their own clock)."""
        out: Dict[str, dict] = {}
        hosts_dir = os.path.join(self.root, "hosts")
        try:
            entries = os.listdir(hosts_dir)
        except OSError:
            return out
        for entry in sorted(entries):
            if not entry.endswith(".lease.json"):
                continue
            lease = _read_json(os.path.join(hosts_dir, entry))
            if lease and "host" in lease:
                out[lease["host"]] = lease
        return out

    def detect_dead(self, record: Optional[dict] = None) -> List[str]:
        """Hosts of the current generation whose lease expired (or was
        never written after a formation grace of one TTL).

        This host itself is never reported dead — a wedged local clock
        must not let a host evict ITSELF and bump the gang under its
        own feet.
        """
        failpoints.fire("membership.detect", payload=self.host)
        rec = record if record is not None else self.read_generation()
        if rec is None:
            return []
        now = self._clock()
        leases = self.live_view()
        dead = []
        for host in rec["hosts"]:
            if host == self.host:
                continue
            lease = leases.get(host)
            if lease is None:
                # Formation grace: a gang member that has not joined
                # yet only counts dead once the record itself is older
                # than one TTL.
                if now - float(rec.get("t", now)) > self.lease_ttl_s:
                    dead.append(host)
            elif now - float(lease.get("t", 0.0)) > self.lease_ttl_s:
                dead.append(host)
        return dead


class LeaseHeartbeat:
    """Daemon thread renewing one host's lease every ``interval_s``.

    The training thread reads :meth:`error` at its membership
    checkpoints; the first renewal failure (stale generation, stolen
    lease, unreachable filesystem) parks here and stops further
    renewals — the trainer surfaces it, the thread never kills the
    process on its own.
    """

    def __init__(self, plane: MembershipPlane, interval_s: float = 1.0):
        self._plane = plane
        self._interval_s = max(float(interval_s), 0.05)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._error: Optional[BaseException] = None
        # guarded-by: self._lock
        self._generation = 0
        # guarded-by: self._lock
        self._step = 0
        # guarded-by: self._lock
        self._epoch = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="membership-lease")

    def start(self, generation: int, step: int = 0,
              epoch: int = 0) -> "LeaseHeartbeat":
        with self._lock:
            self._generation = int(generation)
            self._step = int(step)
            self._epoch = int(epoch)
        self._thread.start()
        return self

    def update(self, generation: int, step: int, epoch: int = 0) -> None:
        """Advance the position the next renewal will advertise."""
        with self._lock:
            self._generation = int(generation)
            self._step = int(step)
            self._epoch = int(epoch)

    def error(self) -> Optional[BaseException]:
        with self._lock:
            return self._error

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self._interval_s * 4)

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            with self._lock:
                generation, step, epoch = (
                    self._generation, self._step, self._epoch)
            try:
                self._plane.renew(generation, step=step, epoch=epoch)
            except BaseException as exc:  # noqa: BLE001 — surfaced to
                # the training thread at its next membership check.
                with self._lock:
                    self._error = exc
                return
