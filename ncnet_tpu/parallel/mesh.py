"""Device-mesh construction for data- and spatial-parallel execution.

The reference is single-GPU (SURVEY.md §2.8); scaling here is green-field:
* axis 'dp' — data parallelism over image pairs (the training axis; gradient
  allreduce rides ICI via `jax.sharding` + jit);
* axis 'sp' — spatial sharding of the 4-D correlation tensor's iA axis for
  the high-resolution InLoc configuration (the long-context analogue; see
  parallel/corr_sharding.py).

On a TPU pod slice, `make_mesh((dp, sp))` lays the axes over the physical
ICI topology via jax.experimental.mesh_utils; on CPU test runs it uses the
virtual host devices.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = ("dp",),
    devices=None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Args:
      shape: mesh shape; defaults to all devices on one 'dp' axis.
      axis_names: one name per mesh dim.
    """
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices[:n])
    except Exception:
        dev_array = np.asarray(devices[:n]).reshape(shape)
    return Mesh(dev_array, axis_names)


def serving_devices(n: Optional[int] = None, backend: Optional[str] = None):
    """Devices for the serving replica pool, in stable id order.

    The fleet builder (serving/fleet.MatchFleet.build) assigns one
    MatchEngine per entry; LOCAL devices only — a replica's engine must
    dispatch without cross-host transfers, and multihost deployments run
    one fleet process per host behind their own balancer
    (parallel/multihost.py). ``n`` requests exactly that many devices
    and raises when the host has fewer (an operator asking for 8
    replicas-with-distinct-devices on a 4-chip host should hear about
    it at startup, not discover 2x-subscribed chips under load).
    """
    devs = sorted(jax.local_devices(backend=backend), key=lambda d: d.id)
    if n is not None:
        if n > len(devs):
            raise ValueError(
                f"asked for {n} serving devices, host has {len(devs)}"
            )
        devs = devs[:n]
    return devs


def shard_map_compat(f, mesh, in_specs, out_specs, check=False):
    """`shard_map` across jax versions: the export moved
    (jax.experimental.shard_map -> jax.shard_map) and the replication
    check kwarg was renamed (check_rep -> check_vma). Every shard_map in
    this repo goes through here so a jax upgrade is a one-line change.
    """
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
    for kw in ("check_vma", "check_rep"):
        try:
            return _sm(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **{kw: check})
        except TypeError:
            continue
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Sharding for a batch-leading array: batch split over `axis`."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
