"""Spatial sharding of the 4-D correlation tensor across a device mesh.

This is the long-context / sequence-parallel analogue for the NCNet workload
(SURVEY.md §2.8 item 2, §5): the InLoc configuration materializes correlation
tensors of ~1.6G elements pre-pool; here the tensor is sharded along its iA
axis across the mesh's 'sp' axis, and:

* mutual matching's max-over-A-positions runs as a `lax.pmax` collective
  (max-over-B stays shard-local);
* the Conv4d stencil gets its iA neighbourhood via halo exchange with
  `lax.ppermute` over ICI — ring-transfer of the 2-cell-deep boundary slabs,
  exactly the ring-attention communication pattern;
* symmetric-mode NeighConsensus re-lays the tensor out with `lax.all_to_all`
  so the A<->B-transposed pass is sharded along *its* leading spatial dim,
  then transfers back — the Ulysses-style all-to-all alternative, used here
  because the transposed pass needs a different axis sharded.

Everything is expressed inside one `shard_map`, so XLA schedules the
collectives and overlaps them with compute.

The reference has no counterpart (single CUDA device, fp16 + maxpool as the
only memory workaround — eval_inloc.py:50, lib/model.py:269-272).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..ops.conv4d import conv4d_prepadded
from ..ops.mutual import EPS
from ..ops.pool4d import maxpool4d


def _halo_exchange(x, pad: int, axis_name: str):
    """Pad dim 2 of the local block with `pad` rows from ring neighbours.

    Boundary shards receive zeros (matching the zero padding of the global
    convolution). x: [b, c, I_loc, ...] -> [b, c, I_loc + 2*pad, ...].
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return jnp.pad(x, ((0, 0), (0, 0), (pad, pad)) + ((0, 0),) * (x.ndim - 3))
    # Send my last `pad` rows to my right neighbour (their left halo) and my
    # first `pad` rows to my left neighbour (their right halo). ppermute
    # leaves unaddressed destinations zero, which realizes the boundary
    # zero padding for shards 0 and n-1.
    right_slab = lax.slice_in_dim(x, x.shape[2] - pad, x.shape[2], axis=2)
    left_slab = lax.slice_in_dim(x, 0, pad, axis=2)
    from_left = lax.ppermute(
        right_slab, axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    from_right = lax.ppermute(
        left_slab, axis_name, [(i + 1, i) for i in range(n - 1)]
    )
    return jnp.concatenate([from_left, x, from_right], axis=2)


# Conv4d over a halo-padded block is exactly the shared prepadded core:
# the halo plays the role of the zero padding.
conv4d_haloed = conv4d_prepadded


def mutual_matching_sharded(corr4d, axis_name: str, eps: float = EPS):
    """Soft mutual-NN filtering on an iA-sharded block.

    max over B positions (dims 4,5) is shard-local; max over A positions
    (dims 2,3) needs the cross-shard `pmax` collective. Elementwise math in
    f32 with the result cast back to the storage dtype (same policy as
    ops.mutual.mutual_matching).
    """
    c = corr4d.astype(jnp.float32)
    max_over_a = lax.pmax(jnp.max(c, axis=(2, 3), keepdims=True), axis_name)
    max_over_b = jnp.max(c, axis=(4, 5), keepdims=True)
    return (
        c * ((c / (max_over_b + eps)) * (c / (max_over_a + eps)))
    ).astype(corr4d.dtype)


def _conv_stack_sharded(params: Sequence[Dict[str, Any]], x, axis_name: str):
    """Conv4d+ReLU stack with per-layer halo exchange on dim 2."""
    for layer in params:
        pad = layer["weight"].shape[0] // 2
        xp = _halo_exchange(x, pad, axis_name) if pad else x
        x = jax.nn.relu(conv4d_haloed(xp, layer["weight"], layer["bias"]))
    return x


def neigh_consensus_sharded(
    params: Sequence[Dict[str, Any]], corr4d, axis_name: str, symmetric: bool = True
):
    """Symmetric NeighConsensus on an iA-sharded correlation block.

    The direct pass convolves with halo exchange along the sharded iA.
    For the transposed pass the tensor is re-laid-out with all_to_all so the
    B-side leading spatial dim (iB) becomes the sharded one, the same stack
    runs, and the result is transferred back and summed.
    """
    direct = _conv_stack_sharded(params, corr4d, axis_name)
    if not symmetric:
        return direct

    n = lax.axis_size(axis_name)
    if n == 1:
        swapped = jnp.transpose(corr4d, (0, 1, 4, 5, 2, 3))
        back = jnp.transpose(
            _conv_stack_sharded(params, swapped, axis_name), (0, 1, 4, 5, 2, 3)
        )
        return direct + back

    # Re-layout: [b,c,I_loc,J,K,L] --all_to_all--> [b,c,I,J,K_loc,L]
    regathered = lax.all_to_all(
        corr4d, axis_name, split_axis=4, concat_axis=2, tiled=True
    )
    swapped = jnp.transpose(regathered, (0, 1, 4, 5, 2, 3))  # [b,c,K_loc,L,I,J]
    conv_t = _conv_stack_sharded(params, swapped, axis_name)
    conv_t = jnp.transpose(conv_t, (0, 1, 4, 5, 2, 3))  # [b,c,I,J,K_loc,L]
    back = lax.all_to_all(conv_t, axis_name, split_axis=2, concat_axis=4, tiled=True)
    return direct + back


def match_pipeline_sharded(params, corr_local, axis_name: str, symmetric: bool = True):
    """mutual -> neigh-consensus -> mutual on an iA-sharded block."""
    x = mutual_matching_sharded(corr_local, axis_name)
    x = neigh_consensus_sharded(params, x, axis_name, symmetric)
    x = mutual_matching_sharded(x, axis_name)
    return x


def make_sharded_match_pipeline(
    mesh: Mesh, axis_name: str = "sp", symmetric: bool = True
):
    """Build a jit-able sharded pipeline over a mesh.

    Returns a function (neigh_consensus_params, corr4d) -> corr4d where
    corr4d is globally shaped [b, 1, I, J, K, L]; I must be divisible by the
    mesh 'sp' axis size (it carries the sharding), and in symmetric mode K
    must be too (the transposed pass re-shards onto K via all_to_all). The
    InLoc input bucketing (cli/eval_inloc.py) guarantees this. Input/output
    shardings: corr split on dim 2, params replicated.
    """
    spec_corr = P(None, None, axis_name, None, None, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), spec_corr),
        out_specs=spec_corr,
        check_vma=False,
    )
    def pipeline(params, corr_local):
        return match_pipeline_sharded(params, corr_local, axis_name, symmetric)

    return jax.jit(pipeline)


def sharded_correlation(feature_a, feature_b, mesh: Mesh, axis_name: str = "sp"):
    """All-pairs correlation with the output sharded along iA.

    feature_a is sharded along its height axis; feature_b is replicated; each
    shard computes its slab of the correlation tensor locally — no
    communication at all (the einsum is embarrassingly parallel over iA).
    """
    spec_fa = P(None, None, axis_name, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec_fa, P()),
        out_specs=P(None, None, axis_name, None, None, None),
        check_vma=False,
    )
    def corr(fa_local, fb):
        c = jnp.einsum(
            "bcij,bckl->bijkl",
            fa_local.astype(jnp.bfloat16),
            fb.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return c[:, None]

    return corr(feature_a, feature_b)
