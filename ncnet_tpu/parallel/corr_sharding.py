"""Spatial sharding of the 4-D correlation tensor across a device mesh.

This is the long-context / sequence-parallel analogue for the NCNet workload
(SURVEY.md §2.8 item 2, §5): the InLoc configuration materializes correlation
tensors of ~1.6G elements pre-pool; here the tensor is sharded along its iA
axis across the mesh's 'sp' axis, and:

* mutual matching's max-over-A-positions runs as a `lax.pmax` collective
  (max-over-B stays shard-local);
* the Conv4d stencil gets its iA neighbourhood via halo exchange with
  `lax.ppermute` over ICI — ring-transfer of the boundary slabs, exactly
  the ring-attention communication pattern;
* symmetric-mode NeighConsensus runs its A<->B-transposed branch as the
  SAME convolution chain with A/B-swapped kernels
  (ops.conv4d.swap_ab_weight): T(stack(T(x))) == stack(x, w_swapped), so
  no re-layout of the tensor is needed — an earlier design used a
  Ulysses-style `lax.all_to_all` re-shard for that branch; the swapped-
  kernel identity makes the ring halo exchange the only communication.

Everything is expressed inside one `shard_map`, so XLA schedules the
collectives and overlaps them with compute.

The reference has no counterpart (single CUDA device, fp16 + maxpool as the
only memory workaround — eval_inloc.py:50, lib/model.py:269-272).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map_compat
from ..ops.conv4d import conv4d_prepadded, swap_ab_weight
from ..ops.mutual import EPS


def _axis_size(axis_name: str) -> int:
    """Static mesh-axis size inside shard_map, across jax versions:
    lax.axis_size appeared in 0.5; older jax resolves psum(1, name) to a
    static int at trace time."""
    try:
        return lax.axis_size(axis_name)
    except AttributeError:
        return int(lax.psum(1, axis_name))


def _halo_exchange(x, pad: int, axis_name: str):
    """Pad dim 2 of the local block with `pad` rows from ring neighbours.

    Boundary shards receive zeros (matching the zero padding of the global
    convolution). x: [b, c, I_loc, ...] -> [b, c, I_loc + 2*pad, ...].
    """
    n = _axis_size(axis_name)
    if n == 1:
        return jnp.pad(x, ((0, 0), (0, 0), (pad, pad)) + ((0, 0),) * (x.ndim - 3))
    # Send my last `pad` rows to my right neighbour (their left halo) and my
    # first `pad` rows to my left neighbour (their right halo). ppermute
    # leaves unaddressed destinations zero, which realizes the boundary
    # zero padding for shards 0 and n-1.
    right_slab = lax.slice_in_dim(x, x.shape[2] - pad, x.shape[2], axis=2)
    left_slab = lax.slice_in_dim(x, 0, pad, axis=2)
    from_left = lax.ppermute(
        right_slab, axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    from_right = lax.ppermute(
        left_slab, axis_name, [(i + 1, i) for i in range(n - 1)]
    )
    return jnp.concatenate([from_left, x, from_right], axis=2)


# Conv4d over a halo-padded block is exactly the shared prepadded core:
# the halo plays the role of the zero padding.
conv4d_haloed = conv4d_prepadded


def mutual_matching_sharded(corr4d, axis_name: str, eps: float = EPS):
    """Soft mutual-NN filtering on an iA-sharded block.

    max over B positions (dims 4,5) is shard-local; max over A positions
    (dims 2,3) needs the cross-shard `pmax` collective. Elementwise math in
    f32 with the result cast back to the storage dtype (same policy as
    ops.mutual.mutual_matching).
    """
    c = corr4d.astype(jnp.float32)
    max_over_a = lax.pmax(jnp.max(c, axis=(2, 3), keepdims=True), axis_name)
    max_over_b = jnp.max(c, axis=(4, 5), keepdims=True)
    return (
        c * ((c / (max_over_b + eps)) * (c / (max_over_a + eps)))
    ).astype(corr4d.dtype)


def _conv_stack_sharded(
    params: Sequence[Dict[str, Any]], x, axis_name: str, swap: bool = False
):
    """Conv4d+ReLU stack with per-layer halo exchange on dim 2.

    swap=True runs the A/B-swapped-kernel chain (the transposed symmetric
    branch, see ops.conv4d.swap_ab_weight) — same layout, same halos.
    """
    for layer in params:
        w = swap_ab_weight(layer["weight"]) if swap else layer["weight"]
        pad = w.shape[0] // 2
        xp = _halo_exchange(x, pad, axis_name) if pad else x
        x = jax.nn.relu(conv4d_haloed(xp, w, layer["bias"]))
    return x


def neigh_consensus_sharded(
    params: Sequence[Dict[str, Any]], corr4d, axis_name: str, symmetric: bool = True
):
    """Symmetric NeighConsensus on an iA-sharded correlation block.

    Both branches convolve the SAME iA-sharded layout with per-layer halo
    exchange: the transposed branch is realized as the swapped-kernel chain
    (T(stack(T(x))) == stack(x, w_swapped), ops.conv4d.swap_ab_weight), so
    no all_to_all re-layout of the correlation tensor is needed — the only
    communication is the ring halo exchange either way.
    """
    direct = _conv_stack_sharded(params, corr4d, axis_name)
    if not symmetric:
        return direct
    return direct + _conv_stack_sharded(params, corr4d, axis_name, swap=True)


def match_pipeline_sharded(params, corr_local, axis_name: str, symmetric: bool = True):
    """mutual -> neigh-consensus -> mutual on an iA-sharded block."""
    x = mutual_matching_sharded(corr_local, axis_name)
    x = neigh_consensus_sharded(params, x, axis_name, symmetric)
    x = mutual_matching_sharded(x, axis_name)
    return x


def make_sharded_match_pipeline(
    mesh: Mesh, axis_name: str = "sp", symmetric: bool = True,
    batch_axis: str | None = None,
):
    """Build a jit-able sharded pipeline over a mesh.

    Returns a function (neigh_consensus_params, corr4d) -> corr4d where
    corr4d is globally shaped [b, 1, I, J, K, L]; I must be divisible by the
    mesh 'sp' axis size (it carries the sharding) — the InLoc input
    bucketing (cli/eval_inloc.py) guarantees this. Input/output shardings:
    corr split on dim 2, params replicated.

    batch_axis: optional second mesh axis carrying the batch dim (dp x sp on
    one 2-D mesh: pairs across 'dp', each pair's iA rows across 'sp'). Batch
    entries are independent, so every collective (pmax, halo ppermute) still
    runs over axis_name only.
    """
    spec_corr = P(batch_axis, None, axis_name, None, None, None)

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(), spec_corr),
        out_specs=spec_corr,
    )
    def pipeline(params, corr_local):
        return match_pipeline_sharded(params, corr_local, axis_name, symmetric)

    return jax.jit(pipeline)


def sharded_correlation(feature_a, feature_b, mesh: Mesh, axis_name: str = "sp"):
    """All-pairs correlation with the output sharded along iA.

    feature_a is sharded along its height axis; feature_b is replicated; each
    shard computes its slab of the correlation tensor locally — no
    communication at all (the einsum is embarrassingly parallel over iA).
    """
    spec_fa = P(None, None, axis_name, None)

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(spec_fa, P()),
        out_specs=P(None, None, axis_name, None, None, None),
    )
    def corr(fa_local, fb):
        c = jnp.einsum(
            "bcij,bckl->bijkl",
            fa_local.astype(jnp.bfloat16),
            fb.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return c[:, None]

    return corr(feature_a, feature_b)
