"""Point-cloud perspective rendering with z-buffering.

Functional replacement for the `ht_Points2Persp` call used by the
reference's dense pose verification (lib_matlab/parfor_nc4d_PV.m:15):
splat an RGBD point cloud through K @ P into a target view, keeping the
nearest point per pixel. Pixels no point reaches are NaN.
"""

from __future__ import annotations

import numpy as np


def points_to_persp(
    rgb: np.ndarray,
    xyz: np.ndarray,
    KP: np.ndarray,
    out_h: int,
    out_w: int,
) -> tuple:
    """Render (rgb_persp [h,w,3], xyz_persp [h,w,3]) of the cloud at KP.

    rgb: [..., 3] colors (any shape; flattened), values passed through.
    xyz: [..., 3] matching global-frame positions (NaN entries skipped).
    KP:  [3, 4] projection K @ [R | t] mapping world -> pixel homogeneous.
    """
    rgb_flat = np.asarray(rgb, dtype=np.float64).reshape(-1, 3)
    xyz_flat = np.asarray(xyz, dtype=np.float64).reshape(-1, 3)
    ok = np.all(np.isfinite(xyz_flat), axis=1)
    rgb_flat, xyz_flat = rgb_flat[ok], xyz_flat[ok]

    proj = xyz_flat @ np.asarray(KP, dtype=np.float64)[:, :3].T + np.asarray(KP)[:, 3]
    z = proj[:, 2]
    front = z > 1e-9
    proj, z, rgb_flat, xyz_flat = proj[front], z[front], rgb_flat[front], xyz_flat[front]

    u = np.round(proj[:, 0] / z).astype(np.int64)
    v = np.round(proj[:, 1] / z).astype(np.int64)
    in_view = (u >= 0) & (u < out_w) & (v >= 0) & (v < out_h)
    u, v, z = u[in_view], v[in_view], z[in_view]
    rgb_flat, xyz_flat = rgb_flat[in_view], xyz_flat[in_view]

    rgb_out = np.full((out_h, out_w, 3), np.nan)
    xyz_out = np.full((out_h, out_w, 3), np.nan)
    if z.size == 0:
        return rgb_out, xyz_out

    # Z-buffer: sort by depth descending, then write — nearest lands last.
    order = np.argsort(-z, kind="stable")
    u, v = u[order], v[order]
    rgb_out[v, u] = rgb_flat[order]
    xyz_out[v, u] = xyz_flat[order]
    return rgb_out, xyz_out
