"""Match backprojection: normalized 2-D matches -> (ray, 3-D point) pairs.

Parity: the preprocessing block of lib_matlab/parfor_NC4D_PE_pnponly.m:
threshold by match score, upsample normalized coordinates to pixels,
look up database-pixel 3-D positions in the RGBD cutout's XYZ map, move
them to the global frame with the scan's alignment transform, and drop
correspondences whose depth is missing (NaN).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .pose import make_intrinsics


@dataclass
class Correspondences2d3d:
    query_px: np.ndarray  # [n, 2] query pixels (x, y)
    db_px: np.ndarray  # [n, 2] database pixels (x, y), integer grid
    rays: np.ndarray  # [n, 3] query bearing vectors K^-1 [u, v, 1]
    points: np.ndarray  # [n, 3] global-frame 3-D points

    def __len__(self) -> int:
        return self.query_px.shape[0]


def matches_to_2d3d(
    matches: np.ndarray,
    xyz_cutout: np.ndarray,
    query_size: tuple,
    focal_length: float,
    scan_transform: np.ndarray | None = None,
    score_thr: float = 0.75,
    max_matches: int | None = None,
    seed: int = 0,
) -> Correspondences2d3d:
    """Build PnP correspondences from one query x pano match list.

    matches:        [n, 5] rows (xq, yq, xdb, ydb, score) with coordinates
                    in [0, 1] ('positive' scale), as written by the InLoc
                    eval (ncnet_tpu/evals/inloc.py; reference
                    eval_inloc.py:199-203).
    xyz_cutout:     [H, W, 3] per-pixel 3-D positions of the database
                    cutout (NaN where depth is missing) — the `XYZcut`
                    array of the InLoc dataset.
    query_size:     (height, width) of the query image in pixels.
    focal_length:   query focal length in pixels.
    scan_transform: optional [4, 4] (or [3, 4]) local->global transform
                    `P_after` applied to the cutout points.
    score_thr:      keep matches with score > thr (reference thr 0.75,
                    compute_densePE_NCNet.m:33).
    max_matches:    optional random subsample (params.ncnet.N_subsample).
    """
    matches = np.asarray(matches, dtype=np.float64).reshape(-1, 5)
    keep = matches[:, 4] > score_thr
    matches = matches[keep]
    if max_matches is not None and matches.shape[0] > max_matches:
        rng = np.random.default_rng(seed)
        matches = matches[rng.choice(matches.shape[0], size=max_matches, replace=False)]

    hq, wq = query_size
    hdb, wdb = xyz_cutout.shape[:2]

    # Query pixels stay continuous (they parameterize the ray); database
    # pixels index the XYZ grid so they are floored and clamped in-bounds
    # (the Matlab code floors then bumps zeros to 1; with 0-based indexing
    # that is a clamp to [0, dim-1]).
    q_px = matches[:, 0:2] * np.array([wq, hq])
    db_px = np.floor(matches[:, 2:4] * np.array([wdb, hdb])).astype(np.int64)
    db_px = np.clip(db_px, 0, [wdb - 1, hdb - 1])

    K = make_intrinsics(focal_length, hq, wq)
    ones = np.ones((q_px.shape[0], 1))
    rays = np.linalg.solve(K, np.concatenate([q_px, ones], axis=1).T).T

    points = np.asarray(xyz_cutout, dtype=np.float64)[db_px[:, 1], db_px[:, 0]]
    if scan_transform is not None:
        T = np.asarray(scan_transform, dtype=np.float64)
        points = points @ T[:3, :3].T + T[:3, 3]

    ok = np.all(np.isfinite(points), axis=1)
    return Correspondences2d3d(
        query_px=q_px[ok], db_px=db_px[ok], rays=rays[ok], points=points[ok]
    )
