"""End-to-end localization driver (parity: compute_densePE_NCNet.m flow).

Per query: load the match file written by the InLoc eval, backproject
each top-ranked pano's matches to 2-D/3-D correspondences, solve P3P
LO-RANSAC per pano, optionally re-rank candidate poses with dense pose
verification, and report the best pose. Per-(query, pano) results are
cached to disk and skipped when present, mirroring the reference's
file-existence idempotency (parfor_NC4D_PE_pnponly.m:6).

The dataset specifics (where cutouts live, scan transforms) are supplied
by caller callbacks so the driver stays dataset-agnostic.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import numpy as np

from .. import obs
from ..utils.py_util import create_file_path
from .backproject import matches_to_2d3d
from .pnp import lo_ransac_p3p
from .pose import pose_distance
from .pose_verification import pose_verification_score


@dataclass
class LocalizationParams:
    score_thr: float = 0.75  # match-score threshold (compute_densePE_NCNet.m:33)
    pnp_thr_deg: float = 0.2  # angular inlier threshold (compute_densePE_NCNet.m:34)
    ransac_iters: int = 10000
    max_matches: Optional[int] = None
    top_n: int = 10
    use_pose_verification: bool = False
    pv_downsample: int = 8
    seed: int = 0


@dataclass
class QueryResult:
    query: str
    poses: list  # [top_n] np.ndarray [3, 4] (NaN where unsolved)
    num_inliers: list  # [top_n] int
    pv_scores: list  # [top_n] float (empty if PV disabled)
    best_index: int = -1

    @property
    def best_pose(self) -> np.ndarray:
        if self.best_index < 0:
            return np.full((3, 4), np.nan)
        return self.poses[self.best_index]


def _cache_path(cache_dir: str, query: str, pano: str) -> str:
    safe_q = query.replace("/", "__")
    safe_p = os.path.splitext(pano.replace("/", "__"))[0]
    return os.path.join(cache_dir, safe_q, safe_p + ".npz")


def localize_queries(
    queries: Sequence[str],
    shortlist: Callable[[str], Sequence[str]],
    load_matches: Callable[[str, int], np.ndarray],
    load_cutout: Callable[[str], tuple],
    query_size: Callable[[str], tuple],
    focal_length: float,
    params: LocalizationParams = LocalizationParams(),
    cache_dir: Optional[str] = None,
    load_query_image: Optional[Callable[[str], np.ndarray]] = None,
    progress: Optional[Callable[[str], None]] = None,
    num_workers: int = 1,
) -> list:
    """Localize every query; returns a list of QueryResult (query order kept).

    shortlist(q)        -> ranked pano names for query q.
    load_matches(q, j)  -> [n, 5] match rows for q's j-th pano.
    load_cutout(pano)   -> (xyz [H, W, 3], scan_transform [4, 4] | None)
                           — plus optionally a third element rgb [H, W, 3]
                           when pose verification is enabled.
    query_size(q)       -> (height, width) of the query image.
    num_workers > 1 localizes queries concurrently (the reference's Matlab
    `parfor` over queries, ir_top100_NC4D_localization_pnponly.m:25): the
    numpy/native stages release the GIL, callbacks must be thread-safe, and
    per-(query, pano) cache paths are disjoint so the resume cache is safe.
    """
    do_pv = params.use_pose_verification and load_query_image is not None

    def localize_one(q: str) -> QueryResult:
        panos = list(shortlist(q))[: params.top_n]
        q_img = load_query_image(q) if do_pv else None
        # One size lookup per query (the CLI's query_size decodes the image).
        q_size = q_img.shape[:2] if q_img is not None else None

        def get_query_size():
            nonlocal q_size
            if q_size is None:
                q_size = query_size(q)
            return q_size

        poses, ninl, pv_scores = [], [], []
        for j, pano in enumerate(panos):
            # Each pano's cutout is loaded at most once and shared between
            # the PnP solve and the pose-verification rescoring.
            cut = None

            def get_cutout():
                nonlocal cut
                if cut is None:
                    cut = load_cutout(pano)
                return cut

            cached = None
            cpath = _cache_path(cache_dir, q, pano) if cache_dir else None
            if cpath and os.path.exists(cpath):
                with np.load(cpath) as z:
                    cached = (z["P"], int(z["num_inliers"]))
            if cached is None:
                xyz, transform = get_cutout()[:2]
                corr = matches_to_2d3d(
                    load_matches(q, j),
                    xyz,
                    get_query_size(),
                    focal_length,
                    scan_transform=transform,
                    score_thr=params.score_thr,
                    max_matches=params.max_matches,
                    seed=params.seed,
                )
                res = lo_ransac_p3p(
                    corr.rays,
                    corr.points,
                    inlier_thr=np.deg2rad(params.pnp_thr_deg),
                    max_iters=params.ransac_iters,
                    seed=params.seed,
                )
                cached = (res.P, res.num_inliers)
                if cpath:
                    create_file_path(cpath)
                    np.savez(cpath, P=res.P, num_inliers=res.num_inliers, inliers=res.inliers)
            poses.append(cached[0])
            ninl.append(cached[1])

            if do_pv:
                full = get_cutout()
                if len(full) < 3:
                    raise ValueError("load_cutout must return (xyz, transform, rgb) for PV")
                score, _ = pose_verification_score(
                    q_img, full[2], full[0], poses[j], focal_length,
                    downsample=params.pv_downsample,
                )
                pv_scores.append(score)

        ranking = pv_scores if do_pv else ninl

        solved = [j for j in range(len(panos)) if np.all(np.isfinite(poses[j]))]
        best = max(solved, key=lambda j: ranking[j]) if solved else -1
        result = QueryResult(
            query=q, poses=poses, num_inliers=ninl,
            pv_scores=pv_scores, best_index=best,
        )
        obs.counter("localization.queries").inc()
        if best < 0:
            obs.counter("localization.unsolved").inc()
        else:
            obs.histogram("localization.best_inliers").observe(ninl[best])
        obs.event(
            "query_localized", query=q, solved=best >= 0,
            best_index=best,
            num_inliers=int(ninl[best]) if best >= 0 else 0,
            n_panos=len(panos),
        )
        if progress is not None:
            progress(q)
        return result

    if num_workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(num_workers) as pool:
            return list(pool.map(localize_one, queries))
    return [localize_one(q) for q in queries]


def evaluate_poses(results: Sequence[QueryResult], gt_poses: dict) -> tuple:
    """(pos_errors [n], ori_errors_deg [n]) vs ground-truth poses.

    gt_poses: {query_name: [3, 4] pose}. Queries with no solved pose get
    inf errors (counted as not localized by localization_rate).
    """
    pos_errs, ori_errs = [], []
    for r in results:
        P = r.best_pose
        gt = gt_poses.get(r.query)
        if gt is None or not np.all(np.isfinite(P)):
            pos_errs.append(np.inf)
            ori_errs.append(np.inf)
            continue
        dpos, dori = pose_distance(np.asarray(gt), P)
        pos_errs.append(dpos)
        ori_errs.append(np.rad2deg(dori))
    return np.asarray(pos_errs), np.asarray(ori_errs)
