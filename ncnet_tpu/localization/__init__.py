"""Visual localization pipeline (InLoc-style PnP + pose verification).

Python/JAX-native replacement for the reference's Matlab L5 layer
(compute_densePE_NCNet.m + lib_matlab/*): consumes the per-query match
files written by the InLoc eval (ncnet_tpu.evals.inloc /
ncnet_tpu.cli.eval_inloc), backprojects database matches to 3-D via the
RGBD cutouts, solves camera pose with P3P LO-RANSAC, optionally
re-ranks candidate poses with dense-descriptor pose verification, and
reports localization-rate-vs-distance-threshold curves.

Design note (TPU-first): where the Matlab pipeline loops over RANSAC
hypotheses one at a time inside `parfor`, this implementation solves
ALL minimal P3P samples in one batched eigendecomposition and scores
all hypotheses against all correspondences with one einsum — the same
work expressed as large dense linear algebra.
"""

from .pnp import p3p_solve, lo_ransac_p3p, RansacResult
from .backproject import matches_to_2d3d, Correspondences2d3d
from .pose import camera_center, pose_distance, make_intrinsics
from .render import points_to_persp
from .dsift import dense_root_sift
from .pose_verification import pose_verification_score
from .curves import localization_rate, plot_localization_curves
from .driver import localize_queries, LocalizationParams

__all__ = [
    "p3p_solve",
    "lo_ransac_p3p",
    "RansacResult",
    "matches_to_2d3d",
    "Correspondences2d3d",
    "camera_center",
    "pose_distance",
    "make_intrinsics",
    "points_to_persp",
    "dense_root_sift",
    "pose_verification_score",
    "localization_rate",
    "plot_localization_curves",
    "localize_queries",
    "LocalizationParams",
]
