"""Camera-pose helpers (parity: lib_matlab/p2c.m, lib_matlab/p2dist.m).

A pose is a [3, 4] matrix P = [R | t] mapping world points to camera
coordinates: x_cam = R @ X + t (no intrinsics folded in).
"""

from __future__ import annotations

import numpy as np


def make_intrinsics(focal_length: float, height: int, width: int) -> np.ndarray:
    """Pinhole K with principal point at the image center.

    Parity: the Kq construction in lib_matlab/parfor_NC4D_PE_pnponly.m:52-54
    (fl on the diagonal, principal point (w/2, h/2)).
    """
    return np.array(
        [
            [focal_length, 0.0, width / 2.0],
            [0.0, focal_length, height / 2.0],
            [0.0, 0.0, 1.0],
        ],
        dtype=np.float64,
    )


def camera_center(P: np.ndarray) -> np.ndarray:
    """Camera center C = -R^T t (parity: lib_matlab/p2c.m)."""
    P = np.asarray(P, dtype=np.float64)
    return -P[:3, :3].T @ P[:3, 3]


def pose_distance(P1: np.ndarray, P2: np.ndarray) -> tuple:
    """(position error [same units as t], orientation error [radians]).

    Parity: lib_matlab/p2dist.m — position error is the distance between
    camera centers; orientation error is the rotation angle of R1^-1 R2.
    """
    c1 = camera_center(P1)
    c2 = camera_center(P2)
    dpos = float(np.linalg.norm(c1 - c2))
    R = np.linalg.solve(np.asarray(P1, dtype=np.float64)[:3, :3], np.asarray(P2, dtype=np.float64)[:3, :3])
    cos_ang = (np.trace(R) - 1.0) / 2.0
    dori = float(np.arccos(np.clip(cos_ang, -1.0, 1.0)))
    return dpos, dori
