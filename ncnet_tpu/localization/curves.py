"""Localization-rate curves (parity: lib_matlab/ht_plotcurve_WUSTL.m:75-99).

A query counts as localized at distance threshold d if its position
error is below d AND its orientation error is within max_orierr_deg.
"""

from __future__ import annotations

import numpy as np

# The reference's threshold grid: 0:0.0625:1 then 1.125:0.125:2 meters.
DEFAULT_THRESHOLDS = np.concatenate(
    [np.arange(0.0, 1.0 + 1e-9, 0.0625), np.arange(1.125, 2.0 + 1e-9, 0.125)]
)


def localization_rate(
    pos_errors: np.ndarray,
    ori_errors_deg: np.ndarray,
    thresholds: np.ndarray = DEFAULT_THRESHOLDS,
    max_orierr_deg: float = 10.0,
) -> np.ndarray:
    """Fraction of queries localized at each distance threshold.

    pos_errors:     [n] position errors (meters); NaN/inf = not localized.
    ori_errors_deg: [n] orientation errors (degrees).
    """
    pos = np.asarray(pos_errors, dtype=np.float64).copy()
    ori = np.asarray(ori_errors_deg, dtype=np.float64)
    pos[~np.isfinite(pos)] = np.inf
    pos[ori > max_orierr_deg] = np.inf
    thr = np.asarray(thresholds, dtype=np.float64)
    return (pos[:, None] < thr[None, :]).mean(axis=0)


def plot_localization_curves(
    curves: dict,
    out_path: str,
    thresholds: np.ndarray = DEFAULT_THRESHOLDS,
) -> None:
    """Write the rate-vs-threshold figure. curves: {label: rates [t]}."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 5))
    for label, rates in curves.items():
        ax.plot(thresholds, np.asarray(rates) * 100.0, marker="o", linewidth=2.0, label=label)
    ax.set_xlim(0, 2)
    ax.set_ylim(0, 80)
    ax.grid(True)
    ax.set_xlabel("Distance threshold [meters]")
    ax.set_ylabel("Correctly localized queries [%]")
    ax.set_xticks(np.arange(0, 2.01, 0.25))
    ax.legend(loc="lower right", fontsize=10)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
