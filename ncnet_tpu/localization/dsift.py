"""Dense rootSIFT descriptors as XLA convolutions.

Stands in for the `vl_phow(..., 'sizes', 8, 'step', 4)` + rootSIFT
stage of the reference's dense pose verification
(lib_matlab/parfor_nc4d_PV.m:28-32). The descriptor is the classic
SIFT layout — a 4x4 spatial grid of orientation histograms (8 bins,
128-D total) with bilinear spatial weighting — computed densely for the
whole image at once: orientation binning is a soft assignment into 8
channels and the spatial triangular window is a separable depthwise
convolution, so the entire field is a few fused XLA ops instead of a
per-keypoint loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

N_ORI = 8
N_SPATIAL = 4  # 4x4 grid of spatial bins


def _triangle_kernel(bin_size: int) -> np.ndarray:
    """Triangular (bilinear) weighting window of one spatial bin."""
    r = np.arange(-bin_size + 1, bin_size, dtype=np.float32)
    return 1.0 - np.abs(r) / bin_size


@functools.partial(jax.jit, static_argnames=("step", "bin_size"))
def _dense_sift_grid(image, step: int, bin_size: int):
    """All-pixels SIFT bin responses, then sampled on the frame grid.

    image: [h, w] float grayscale. Returns (frames [n, 2] (x, y) pixel
    centers, descriptors [n, 128] rootSIFT).
    """
    img = image.astype(jnp.float32)
    h, w = img.shape

    gx = jnp.zeros_like(img).at[:, 1:-1].set((img[:, 2:] - img[:, :-2]) * 0.5)
    gy = jnp.zeros_like(img).at[1:-1, :].set((img[2:, :] - img[:-2, :]) * 0.5)
    mag = jnp.sqrt(gx * gx + gy * gy)
    ang = jnp.arctan2(gy, gx)  # [-pi, pi]

    # Soft orientation assignment: each pixel contributes to its two
    # nearest of the 8 orientation bins with linear weights.
    o = (ang / (2.0 * jnp.pi)) * N_ORI  # [-4, 4)
    o = jnp.mod(o, N_ORI)
    lo = jnp.floor(o)
    frac = o - lo
    lo_i = lo.astype(jnp.int32) % N_ORI
    hi_i = (lo_i + 1) % N_ORI
    ori = jnp.zeros((N_ORI, h, w), jnp.float32)
    ori = ori.at[lo_i, jnp.arange(h)[:, None], jnp.arange(w)[None, :]].add(mag * (1.0 - frac))
    ori = ori.at[hi_i, jnp.arange(h)[:, None], jnp.arange(w)[None, :]].add(mag * frac)

    # Separable triangular spatial pooling (one bin's support).
    k = jnp.asarray(_triangle_kernel(bin_size))
    pad = bin_size - 1

    def conv1d(x, axis):
        kern = k.reshape((-1, 1) if axis == 1 else (1, -1))
        return jax.lax.conv_general_dilated(
            x[:, None],
            kern[None, None],
            window_strides=(1, 1),
            padding=[(pad, pad), (0, 0)] if axis == 1 else [(0, 0), (pad, pad)],
        )[:, 0]

    pooled = conv1d(conv1d(ori, 1), 2)  # [8, h, w] bin response centered at each pixel

    # Frame grid: descriptor center c covers [c - 2*bin, c + 2*bin].
    half = 2 * bin_size
    ys = jnp.arange(half, h - half + 1, step)
    xs = jnp.arange(half, w - half + 1, step)

    # Spatial bin centers relative to the descriptor center.
    offs = (jnp.arange(N_SPATIAL) - (N_SPATIAL - 1) / 2.0) * bin_size  # [-12,-4,4,12] for bin 8
    offs = jnp.round(offs).astype(jnp.int32)

    by = ys[:, None] + offs[None, :]  # [ny, 4]
    bx = xs[:, None] + offs[None, :]  # [nx, 4]
    by = jnp.clip(by, 0, h - 1)
    bx = jnp.clip(bx, 0, w - 1)

    # Gather: [8, ny, 4, nx, 4] -> [ny, nx, 4(y), 4(x), 8]
    g = pooled[:, by[:, :, None, None], bx[None, None, :, :]]
    g = jnp.transpose(g, (1, 3, 2, 4, 0))
    desc = g.reshape(ys.shape[0] * xs.shape[0], N_SPATIAL * N_SPATIAL * N_ORI)

    # SIFT normalization: L2, clamp 0.2, re-L2 — then rootSIFT (L1 + sqrt).
    def l2n(d):
        return d / jnp.maximum(jnp.linalg.norm(d, axis=-1, keepdims=True), 1e-9)

    desc = l2n(jnp.minimum(l2n(desc), 0.2))
    desc = jnp.sqrt(desc / jnp.maximum(jnp.sum(desc, axis=-1, keepdims=True), 1e-9))

    fy, fx = jnp.meshgrid(ys, xs, indexing="ij")
    frames = jnp.stack([fx.reshape(-1), fy.reshape(-1)], axis=-1)
    return frames, desc


def dense_root_sift(image, step: int = 4, bin_size: int = 8):
    """Dense rootSIFT over a grayscale image.

    Returns (frames [n, 2] int (x, y), descriptors [n, 128] float32).
    """
    image = jnp.asarray(image)
    if image.ndim == 3:
        image = image @ jnp.asarray([0.299, 0.587, 0.114], image.dtype)
    frames, desc = _dense_sift_grid(image, step=step, bin_size=bin_size)
    return np.asarray(frames), np.asarray(desc)
