"""Dense pose verification: re-score candidate poses by rendered appearance.

Parity: lib_matlab/parfor_nc4d_PV.m — render the scan's RGBD cloud at
the candidate pose (downsampled 8x), normalize both images over the
valid-coverage mask, compare dense rootSIFT descriptors, and score the
pose as 1 / median descriptor error. Poses whose render covers nothing
(or that are NaN) score 0.
"""

from __future__ import annotations

import numpy as np

from .dsift import dense_root_sift
from .pose import make_intrinsics
from .render import points_to_persp


def _to_gray(img: np.ndarray) -> np.ndarray:
    img = np.asarray(img, dtype=np.float64)
    if img.ndim == 3:
        img = img @ np.array([0.299, 0.587, 0.114])
    return img


def _normalize_over_mask(img: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Zero-mean / unit-std normalization computed over the masked pixels
    (parity: the image_normalization call in parfor_nc4d_PV.m:21-24)."""
    vals = img[mask]
    if vals.size == 0:
        return img
    std = vals.std()
    return (img - vals.mean()) / (std if std > 1e-9 else 1.0)


def pose_verification_score(
    query_image: np.ndarray,
    rgb_cloud: np.ndarray,
    xyz_cloud: np.ndarray,
    P: np.ndarray,
    focal_length: float,
    downsample: int = 8,
    step: int = 4,
    bin_size: int = 8,
) -> tuple:
    """(score, error_map) for one candidate pose.

    query_image:  [H, W, 3] (or grayscale) query at full resolution.
    rgb/xyz_cloud: the scan's colored point cloud (any shape, matched).
    P:            [3, 4] candidate pose (world -> camera).
    focal_length: query focal in pixels at FULL resolution; scaled by
                  1/downsample like the reference's `fl * dslevel`.
    """
    P = np.asarray(P, dtype=np.float64)
    if not np.all(np.isfinite(P)):
        return 0.0, None

    q = _to_gray(query_image)
    h = max(1, q.shape[0] // downsample)
    w = max(1, q.shape[1] // downsample)
    # Box-ish downsample by striding (appearance statistics only).
    q_small = np.asarray(
        np.add.reduceat(
            np.add.reduceat(q[: h * downsample, : w * downsample], np.arange(0, h * downsample, downsample), axis=0),
            np.arange(0, w * downsample, downsample),
            axis=1,
        )
    ) / float(downsample * downsample)

    K = make_intrinsics(focal_length / downsample, h, w)
    rgb_persp, xyz_persp = points_to_persp(rgb_cloud, xyz_cloud, K @ P, h, w)
    valid = np.all(np.isfinite(xyz_persp), axis=-1)
    if not valid.any():
        return 0.0, None

    synth = _to_gray(rgb_persp)
    synth = np.where(valid, synth, 0.0)
    q_norm = _normalize_over_mask(q_small, valid)
    s_norm = _normalize_over_mask(synth, valid)

    f_q, d_q = dense_root_sift(q_norm, step=step, bin_size=bin_size)
    f_s, d_s = dense_root_sift(s_norm, step=step, bin_size=bin_size)
    # Identical grids by construction; evaluate only frames on valid pixels.
    on_valid = valid[f_s[:, 1], f_s[:, 0]]
    if not on_valid.any():
        return 0.0, None

    err = np.linalg.norm(d_q[on_valid] - d_s[on_valid], axis=1)
    med = float(np.median(err))
    score = 1.0 / med if med > 1e-12 else float("inf")

    err_map = np.full(valid.shape, np.nan)
    err_map[f_s[on_valid, 1], f_s[on_valid, 0]] = err
    return score, err_map
