"""P3P absolute-pose solver + LO-RANSAC.

Functional replacement for the `ht_lo_ransac_p3p` call in the reference
Matlab pipeline (lib_matlab/parfor_NC4D_PE_pnponly.m:77: P3P LO-RANSAC,
angular inlier threshold in radians, 10000 iterations). The solver itself
lives in the external InLoc_demo repo, so this is a from-scratch
implementation:

  * Minimal solver: Grunert's classic three-point resection (the quartic
    in the distance ratio), solved for ALL RANSAC samples at once as a
    batch of 4x4 companion-matrix eigendecompositions.
  * Pose from distances: batched absolute orientation (Kabsch/SVD)
    between the camera-frame points s_i * f_i and the world points.
  * Scoring: angular error between observed unit rays and predicted rays
    for all hypotheses x all correspondences in one einsum.
  * LO step: iterative object-space refinement on the inlier set
    (alternate depth estimation and absolute orientation).

Everything is vectorized numpy — the hypothesis sweep is a handful of
large dense ops rather than a Matlab `for` over samples.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class RansacResult:
    P: np.ndarray  # [3, 4] world->camera pose, or NaN if unsolved
    inliers: np.ndarray  # [n] bool
    num_inliers: int = 0
    # Mean angular error (radians) of the inliers under the final pose.
    inlier_error: float = float("inf")

    @property
    def ok(self) -> bool:
        return bool(np.all(np.isfinite(self.P)))


def _normalize_rows(v: np.ndarray, eps: float = 1e-12) -> np.ndarray:
    return v / np.maximum(np.linalg.norm(v, axis=-1, keepdims=True), eps)


def _quartic_roots_batched(coeffs: np.ndarray) -> np.ndarray:
    """Real roots of a batch of quartics via companion-matrix eigenvalues.

    coeffs: [m, 5] with coeffs[:, 0] the x^4 coefficient. Returns [m, 4]
    real parts, with NaN where the root has a significant imaginary part
    or the quartic degenerates (leading coefficient ~ 0).
    """
    coeffs = np.asarray(coeffs, dtype=np.float64)
    m = coeffs.shape[0]
    lead = coeffs[:, :1]
    bad_lead = np.abs(lead[:, 0]) < 1e-12
    safe_lead = np.where(bad_lead[:, None], 1.0, lead)
    monic = coeffs[:, 1:] / safe_lead  # [m, 4]

    comp = np.zeros((m, 4, 4), dtype=np.float64)
    comp[:, 0, :] = -monic
    comp[:, 1, 0] = 1.0
    comp[:, 2, 1] = 1.0
    comp[:, 3, 2] = 1.0
    roots = np.linalg.eigvals(comp)  # [m, 4] complex
    real = np.real(roots)
    imag_ok = np.abs(np.imag(roots)) < 1e-6 * np.maximum(1.0, np.abs(real))
    real = np.where(imag_ok, real, np.nan)
    real[bad_lead] = np.nan
    return real


def p3p_solve(rays: np.ndarray, points: np.ndarray) -> np.ndarray:
    """Grunert P3P for a batch of minimal samples.

    rays:   [m, 3, 3] unit bearing vectors in the camera frame.
    points: [m, 3, 3] corresponding world points.
    Returns [m, 4, 3, 4] candidate poses (world->camera), NaN-padded
    where fewer than 4 real solutions exist.
    """
    f = _normalize_rows(np.asarray(rays, dtype=np.float64))
    X = np.asarray(points, dtype=np.float64)
    m = f.shape[0]

    # Side lengths: a opposite vertex 1, b opposite vertex 2, c opposite 3.
    a = np.linalg.norm(X[:, 1] - X[:, 2], axis=-1)
    b = np.linalg.norm(X[:, 0] - X[:, 2], axis=-1)
    c = np.linalg.norm(X[:, 0] - X[:, 1], axis=-1)
    cos_a = np.einsum("mi,mi->m", f[:, 1], f[:, 2])
    cos_b = np.einsum("mi,mi->m", f[:, 0], f[:, 2])
    cos_g = np.einsum("mi,mi->m", f[:, 0], f[:, 1])

    with np.errstate(divide="ignore", invalid="ignore"):
        b2 = np.maximum(b * b, 1e-18)
        acb = (a * a - c * c) / b2  # (a^2 - c^2) / b^2
        apb = (a * a + c * c) / b2  # (a^2 + c^2) / b^2
        bc = (b * b - c * c) / b2
        ba = (b * b - a * a) / b2
        a2b = (a * a) / b2
        c2b = (c * c) / b2

        A4 = (acb - 1.0) ** 2 - 4.0 * c2b * cos_a**2
        A3 = 4.0 * (
            acb * (1.0 - acb) * cos_b
            - (1.0 - apb) * cos_a * cos_g
            + 2.0 * c2b * cos_a**2 * cos_b
        )
        A2 = 2.0 * (
            acb**2
            - 1.0
            + 2.0 * acb**2 * cos_b**2
            + 2.0 * bc * cos_a**2
            - 4.0 * apb * cos_a * cos_b * cos_g
            + 2.0 * ba * cos_g**2
        )
        A1 = 4.0 * (
            -acb * (1.0 + acb) * cos_b
            + 2.0 * a2b * cos_g**2 * cos_b
            - (1.0 - apb) * cos_a * cos_g
        )
        A0 = (1.0 + acb) ** 2 - 4.0 * a2b * cos_g**2

    coeffs = np.stack([A4, A3, A2, A1, A0], axis=-1)  # [m, 5]
    v = _quartic_roots_batched(coeffs)  # [m, 4]  v = s3 / s1

    with np.errstate(divide="ignore", invalid="ignore"):
        # Back-substitution (Haralick et al., review of P3P solutions):
        # u = s2/s1 from the linear relation between the two remaining
        # constraints once v is fixed.
        num = (-1.0 + acb[:, None]) * v**2 - 2.0 * acb[:, None] * cos_b[:, None] * v + 1.0 + acb[:, None]
        den = 2.0 * (cos_g[:, None] - v * cos_a[:, None])
        u = num / den
        s1 = b[:, None] / np.sqrt(np.maximum(1.0 + v**2 - 2.0 * v * cos_b[:, None], 1e-18))
        s2 = u * s1
        s3 = v * s1

    valid = np.isfinite(v) & np.isfinite(u) & (s1 > 0) & (s2 > 0) & (s3 > 0)
    s = np.stack([s1, s2, s3], axis=-1)  # [m, 4, 3]
    s = np.where(valid[..., None], s, np.nan)

    # Camera-frame points for every candidate: [m, 4, 3(points), 3(xyz)]
    cam_pts = s[..., None] * f[:, None, :, :]
    world_pts = np.broadcast_to(X[:, None, :, :], cam_pts.shape)
    poses = _absolute_orientation(world_pts.reshape(-1, 3, 3), cam_pts.reshape(-1, 3, 3))
    return poses.reshape(m, 4, 3, 4)


def _absolute_orientation(world: np.ndarray, cam: np.ndarray) -> np.ndarray:
    """Batched rigid alignment: find [R|t] with cam_i ~= R @ world_i + t.

    world, cam: [n, k, 3]. Returns [n, 3, 4] (NaN rows propagate to NaN
    poses). Kabsch via SVD of the centered covariance.
    """
    world = np.asarray(world, dtype=np.float64)
    cam = np.asarray(cam, dtype=np.float64)
    bad = ~np.all(np.isfinite(cam), axis=(1, 2)) | ~np.all(np.isfinite(world), axis=(1, 2))
    cam_safe = np.where(bad[:, None, None], 0.0, cam)
    world_safe = np.where(bad[:, None, None], 0.0, world)

    wc = world_safe.mean(axis=1, keepdims=True)
    cc = cam_safe.mean(axis=1, keepdims=True)
    H = np.einsum("nki,nkj->nij", world_safe - wc, cam_safe - cc)  # [n, 3, 3]
    # Guard rank-deficient H from degenerate samples.
    H = H + 1e-12 * np.eye(3)
    U, _, Vt = np.linalg.svd(H)
    d = np.sign(np.linalg.det(np.einsum("nij,njk->nik", np.transpose(Vt, (0, 2, 1)), np.transpose(U, (0, 2, 1)))))
    D = np.zeros((world.shape[0], 3, 3))
    D[:, 0, 0] = 1.0
    D[:, 1, 1] = 1.0
    D[:, 2, 2] = d
    R = np.einsum("nij,njk,nkl->nil", np.transpose(Vt, (0, 2, 1)), D, np.transpose(U, (0, 2, 1)))
    t = cc[:, 0, :] - np.einsum("nij,nj->ni", R, wc[:, 0, :])
    P = np.concatenate([R, t[:, :, None]], axis=-1)
    P = np.where(bad[:, None, None], np.nan, P)
    return P


def _angular_errors(poses: np.ndarray, rays: np.ndarray, points: np.ndarray) -> np.ndarray:
    """Angle between observed rays and predicted rays for every pose.

    poses: [h, 3, 4]; rays: [n, 3] (unit); points: [n, 3]. Returns [h, n]
    radians (NaN-poses and behind-camera points give pi).
    """
    R = poses[:, :, :3]
    t = poses[:, :, 3]
    pred = np.einsum("hij,nj->hni", R, points) + t[:, None, :]  # [h, n, 3]
    pred_n = _normalize_rows(pred)
    cosang = np.einsum("hni,ni->hn", pred_n, rays)
    cosang = np.where(np.isfinite(cosang), cosang, -1.0)
    return np.arccos(np.clip(cosang, -1.0, 1.0))


def _refine_pose(P: np.ndarray, rays: np.ndarray, points: np.ndarray, iters: int = 10) -> np.ndarray:
    """Local optimization: object-space alternation on the inlier set.

    Alternates (1) per-point depth = projection of the transformed point
    onto its observed ray and (2) absolute orientation against the
    re-scaled rays. Monotonically decreases object-space error.
    """
    P = P.copy()
    for _ in range(iters):
        trans = points @ P[:, :3].T + P[:, 3]
        depths = np.maximum(np.einsum("ni,ni->n", trans, rays), 1e-9)
        cam_pts = depths[:, None] * rays
        P = _absolute_orientation(points[None], cam_pts[None])[0]
        if not np.all(np.isfinite(P)):
            return np.full((3, 4), np.nan)
    return P


def lo_ransac_p3p(
    rays: np.ndarray,
    points: np.ndarray,
    inlier_thr: float,
    max_iters: int = 10000,
    seed: int = 0,
    lo_iters: int = 10,
    backend: str = "auto",
) -> RansacResult:
    """LO-RANSAC over batched Grunert P3P.

    rays:       [n, 3] bearing vectors in the camera frame (normalized
                internally); e.g. K^-1 @ [u, v, 1].
    points:     [n, 3] world points.
    inlier_thr: angular threshold in RADIANS (the reference passes
                pnp_thr * pi / 180 with pnp_thr = 0.2 degrees,
                compute_densePE_NCNet.m:34).
    max_iters:  number of minimal samples (all solved in one batch).
    backend:    'auto' (native C++ solver when built, else numpy),
                'native', or 'numpy'. The two backends draw different
                random samples but implement the same solver and accept
                rules.

    Returns RansacResult with P = [R|t] (world->camera) and the inlier
    mask under the final locally-optimized pose.
    """
    if backend not in ("auto", "native", "numpy"):
        raise ValueError(f"unknown backend {backend!r}; use 'auto', 'native', or 'numpy'")
    if backend != "numpy":
        from ncnet_tpu import native

        if native.available():
            return native.lo_ransac_p3p_native(
                rays, points, inlier_thr,
                max_iters=max_iters, seed=seed, lo_iters=lo_iters,
            )
        if backend == "native":
            raise RuntimeError("native P3P backend requested but unavailable")
    rays = _normalize_rows(np.asarray(rays, dtype=np.float64))
    points = np.asarray(points, dtype=np.float64)
    n = rays.shape[0]
    if n < 3:
        return RansacResult(P=np.full((3, 4), np.nan), inliers=np.zeros(n, dtype=bool))

    rng = np.random.default_rng(seed)
    # All minimal samples drawn up front; duplicates within a sample are
    # discarded by the degenerate-quartic guard in p3p_solve.
    idx = rng.integers(0, n, size=(max_iters, 3))
    # Ensure distinct indices per sample (vectorized rejection resampling).
    if n == 3:
        idx = rng.permuted(np.tile(np.arange(3), (max_iters, 1)), axis=1)
    else:
        def collisions(ix):
            return (ix[:, 0] == ix[:, 1]) | (ix[:, 0] == ix[:, 2]) | (ix[:, 1] == ix[:, 2])

        collide = collisions(idx)
        while collide.any():
            idx[collide] = rng.integers(0, n, size=(int(collide.sum()), 3))
            collide = collisions(idx)

    cand = p3p_solve(rays[idx], points[idx]).reshape(-1, 3, 4)  # [m*4, 3, 4]
    finite = np.all(np.isfinite(cand), axis=(1, 2))
    cand = cand[finite]
    if cand.shape[0] == 0:
        return RansacResult(P=np.full((3, 4), np.nan), inliers=np.zeros(n, dtype=bool))

    # Score every hypothesis against every correspondence in one sweep,
    # chunked to bound memory for very large hypothesis counts.
    best_count = -1
    best_pose = None
    chunk = max(1, int(4e7) // max(n, 1))
    for start in range(0, cand.shape[0], chunk):
        errs = _angular_errors(cand[start : start + chunk], rays, points)
        counts = (errs < inlier_thr).sum(axis=1)
        j = int(np.argmax(counts))
        if counts[j] > best_count:
            best_count = int(counts[j])
            best_pose = cand[start + j]

    if best_pose is None or best_count < 3:
        return RansacResult(P=np.full((3, 4), np.nan), inliers=np.zeros(n, dtype=bool))

    # Local optimization: refine on the inlier set, keep if it improves.
    P = best_pose
    for _ in range(2):
        inl = _angular_errors(P[None], rays, points)[0] < inlier_thr
        if inl.sum() < 3:
            break
        P_ref = _refine_pose(P, rays[inl], points[inl], iters=lo_iters)
        if not np.all(np.isfinite(P_ref)):
            break
        new_inl = _angular_errors(P_ref[None], rays, points)[0] < inlier_thr
        if new_inl.sum() >= inl.sum():
            P = P_ref
        else:
            break

    errs = _angular_errors(P[None], rays, points)[0]
    inliers = errs < inlier_thr
    mean_err = float(errs[inliers].mean()) if inliers.any() else float("inf")
    return RansacResult(P=P, inliers=inliers, num_inliers=int(inliers.sum()), inlier_error=mean_err)
