"""Evaluation: PCK keypoint transfer, TSS flow output, InLoc match export."""

from .pck import pck, pck_metric
from .flow_eval import dense_warp_grid, write_flow_output
from .inloc import (
    c2f_device_matches,
    dedup_matches,
    extract_inloc_matches,
    inloc_device_matches,
    inloc_matches_from_consensus,
    write_matches_mat,
    matches_buffer,
    fill_matches,
)

__all__ = [
    "pck",
    "pck_metric",
    "dense_warp_grid",
    "write_flow_output",
    "c2f_device_matches",
    "dedup_matches",
    "extract_inloc_matches",
    "inloc_device_matches",
    "inloc_matches_from_consensus",
    "write_matches_mat",
    "matches_buffer",
    "fill_matches",
]
