"""Evaluation: PCK keypoint transfer, TSS flow output, InLoc match export."""

from .pck import pck, pck_metric
from .agreement import (
    delta_within_gate,
    match_table_agreement,
    mutual_nn_fraction,
    within_tolerance,
)
from .flow_eval import dense_warp_grid, write_flow_output
from .inloc import (
    c2f_device_matches,
    dedup_matches,
    extract_inloc_matches,
    inloc_device_matches,
    inloc_matches_from_consensus,
    write_matches_mat,
    matches_buffer,
    fill_matches,
)

__all__ = [
    "pck",
    "pck_metric",
    "delta_within_gate",
    "match_table_agreement",
    "mutual_nn_fraction",
    "within_tolerance",
    "dense_warp_grid",
    "write_flow_output",
    "c2f_device_matches",
    "dedup_matches",
    "extract_inloc_matches",
    "inloc_device_matches",
    "inloc_matches_from_consensus",
    "write_matches_mat",
    "matches_buffer",
    "fill_matches",
]
