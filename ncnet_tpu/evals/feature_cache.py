"""Cross-query pano feature cache for the InLoc matching CLI.

The InLoc shortlists repeat panos heavily across the 356 queries, yet the
reference recomputes every pano's backbone features for every query x pano
pair (eval_inloc.py:124-137 — 3,560 forward passes). The backbone is the
largest per-pano device cost (~87 ms of ~300 on v5e, round-2 trace), so a
TPU-first redesign caches pano features ACROSS queries: a hit skips the
pano backbone entirely and dispatches only the correlation/consensus/
extraction half of the step.

Keying and bounds:
  * key = (model_key, pano path, resized (H, W) bucket) — model_key
    identifies the weights (checkpoint path + file mtime, or the init
    seed), so a cache can never serve features from different weights;
    the resize bucket key keeps distinct compilation shapes distinct.
  * bounded host-memory LRU by BYTES (features at the InLoc bucket are
    ~57 MB per pano: 1024ch x 192x144 bf16 — the miss program rounds its
    f32 features through bf16 before the D2H store, which is lossless
    downstream because every correlation path casts features to bf16 as
    its first op; the CLI's default 4 GiB budget holds ~75 panos, several
    10-pano shortlist windows plus reuse locality).
  * optional disk tier (``disk_dir``): entries evicted from memory stay
    on disk (npz keyed by a hash of the key) and promote back on hit —
    sized for re-runs and multi-process sweeps, where the backbone cost
    of the whole pano set is paid at most once per weights.

This module is pure host-side bookkeeping (numpy + files); the caller
owns device placement (jnp.asarray on hit) and extraction (device_get
on store).
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
import uuid
from collections import OrderedDict
from typing import Optional, Tuple

import ml_dtypes  # ships with jax
import numpy as np


def model_cache_key(checkpoint: str, seed: int = 0) -> str:
    """Stable identifier for the weights producing the cached features.

    A checkpoint is identified by its resolved path + params.npz mtime
    (content hashing 100+ MB of weights per CLI start is not worth it;
    an mtime bump after a re-save correctly invalidates). Without a
    checkpoint, features come from the deterministic init -> the seed
    identifies them.
    """
    if checkpoint:
        path = os.path.abspath(os.path.normpath(checkpoint))
        params_file = os.path.join(path, "params.npz")
        try:
            mtime = os.stat(params_file).st_mtime_ns
        except OSError:
            try:
                mtime = os.stat(path).st_mtime_ns
            except OSError:
                mtime = 0
        return f"{path}@{mtime}"
    return f"init-seed-{seed}"


class PanoFeatureCache:
    """Byte-bounded LRU of pano backbone features, optional disk tier."""

    def __init__(self, max_bytes: int, disk_dir: Optional[str] = None,
                 model_key: str = "", store_dtype=None):
        """store_dtype: when set (eval_inloc passes bf16), every entry —
        including pre-existing disk entries written before the bf16
        change — is normalized to that dtype on load/store, keeping the
        LRU at one entry size and the hit program at one dtype
        specialization. None (default) keeps the container
        dtype-faithful."""
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        self.max_bytes = int(max_bytes)
        self.disk_dir = disk_dir
        self.model_key = model_key
        self.store_dtype = np.dtype(store_dtype) if store_dtype else None
        self._lru: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        # get() runs on the CLI's decode-prefetch thread while put() runs
        # on the main thread; LRU reordering + eviction need the lock.
        self._lock = threading.Lock()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.disk_hits = 0
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)

    def _key(self, pano_path: str, shape: Tuple[int, int]) -> tuple:
        return (self.model_key, pano_path, tuple(shape))

    @staticmethod
    def _hash(key: tuple) -> str:
        return hashlib.sha1(repr(key).encode()).hexdigest()

    @contextlib.contextmanager
    def _disk_lock(self):
        """Serialize cross-process compound disk mutations.

        Single writes are already atomic (tmp + rename, _disk_write);
        this guards the MULTI-step sequences a fleet of engines — or
        several server processes sharing one disk_dir — can interleave:
        the legacy migration's write-new-then-unlink-old, and put()'s
        exists-probe-then-write. An advisory ``fcntl.flock`` on a
        sidecar lock file; where flock is unavailable (non-posix) the
        in-process lock still holds and the atomic renames keep the
        worst cross-process outcome at a redundant write, never a
        corrupt or vanished entry."""
        if not self.disk_dir:
            yield
            return
        fh = None
        try:
            import fcntl

            fh = open(os.path.join(self.disk_dir, ".cache.lock"), "a+b")
            fcntl.flock(fh, fcntl.LOCK_EX)
        except (ImportError, OSError):
            if fh is not None:
                fh.close()
                fh = None
        try:
            yield
        finally:
            if fh is not None:
                try:
                    import fcntl

                    fcntl.flock(fh, fcntl.LOCK_UN)
                except (ImportError, OSError):
                    pass
                fh.close()

    def _disk_path(self, key: tuple) -> str:
        # feat2_: the uint16-view+tag format. Versioned name so a reader
        # from a pre-bf16 build sharing this dir misses (recomputes)
        # instead of consuming the uint16 view as f32 features.
        return os.path.join(self.disk_dir, f"feat2_{self._hash(key)}.npz")

    def _legacy_disk_path(self, key: tuple) -> str:
        # feat_: pre-bf16 builds' raw-npz entries (untagged f32).
        return os.path.join(self.disk_dir, f"feat_{self._hash(key)}.npz")

    def get(self, pano_path: str, shape: Tuple[int, int]):
        """Cached features for (pano, resize bucket), or None.

        Disk-tier hits promote back into the memory LRU.
        """
        key = self._key(pano_path, shape)
        with self._lock:
            feats = self._lru.get(key)
            if feats is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                return feats
        if self.disk_dir:
            import zipfile

            path = self._disk_path(key)
            legacy_path = self._legacy_disk_path(key)
            feats = read_path = None
            # Probe the versioned format first, then the pre-bf16 one; a
            # partial/corrupt file (killed run, racing migration) falls
            # through to the next candidate instead of shadowing it.
            for cand in (path, legacy_path):
                if not os.path.exists(cand):
                    continue
                try:
                    with np.load(cand) as z:
                        f = z["feats"]
                        # npz cannot round-trip the ml_dtypes bf16 dtype
                        # (it loads back as opaque V2); entries are saved
                        # as a uint16 view plus this tag.
                        if "dtype" in z and str(z["dtype"][()]) == "bfloat16":
                            f = f.view(ml_dtypes.bfloat16)
                except (OSError, ValueError, KeyError, zipfile.BadZipFile):
                    continue  # a miss for this candidate, not a crash
                feats, read_path = f, cand
                break
            if (feats is not None and self.store_dtype is not None
                    and feats.dtype != self.store_dtype):
                # Legacy disk entry in another dtype (pre-bf16 f32):
                # round it the same way a fresh store would (identical
                # values downstream — the correlation casts to bf16
                # first regardless) and write the half-size entry under
                # the versioned name. Only once that write has landed is
                # the old file dropped (a pre-bf16 reader sharing the
                # dir then misses and recomputes — safe; a failed write
                # must not orphan the only disk copy).
                feats = feats.astype(self.store_dtype)
                with self._disk_lock():
                    if (self._disk_write(path, feats)
                            and read_path == legacy_path):
                        try:
                            os.unlink(legacy_path)
                        except OSError:
                            pass
            if feats is not None:
                with self._lock:
                    self.hits += 1
                    self.disk_hits += 1
                self._store_mem(key, feats)
                return feats
        with self._lock:
            self.misses += 1
        return None

    def put(self, pano_path: str, shape: Tuple[int, int],
            feats: np.ndarray) -> None:
        key = self._key(pano_path, shape)
        with self._lock:
            if key in self._lru:
                return
        feats = np.asarray(feats)
        if self.store_dtype is not None and feats.dtype != self.store_dtype:
            feats = feats.astype(self.store_dtype)
        if self.disk_dir:
            path = self._disk_path(key)
            with self._disk_lock():
                if not os.path.exists(path):
                    self._disk_write(path, feats)
        self._store_mem(key, feats)

    def _disk_write(self, path: str, feats: np.ndarray) -> bool:
        # tmp + rename: a killed run must not leave a truncated npz that
        # later loads as garbage features. The tmp name is unique per
        # WRITE (pid + uuid): concurrent sweeps sharing disk_dir migrate
        # the same popular panos at startup, same-process pool threads
        # can store a shortlist-duplicated pano twice, and two writers
        # on ONE shared tmp inode could publish a half-written file
        # through the other's os.replace.
        tmp = f"{path}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
        if feats.dtype == ml_dtypes.bfloat16:
            storable, tag = feats.view(np.uint16), "bfloat16"
        else:
            storable, tag = feats, str(feats.dtype)
        try:
            # Through a handle: np.savez(str) would append .npz to the
            # tmp name and the rename would miss it.
            with open(tmp, "wb") as fh:
                np.savez(fh, feats=storable, dtype=tag)
            os.replace(tmp, path)
            return True
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    def _store_mem(self, key: tuple, feats: np.ndarray) -> None:
        if feats.nbytes > self.max_bytes:
            return  # larger than the whole budget: disk-only (if any)
        with self._lock:
            if key in self._lru:
                return
            self._lru[key] = feats
            self._bytes += feats.nbytes
            while self._bytes > self.max_bytes and len(self._lru) > 1:
                _, old = self._lru.popitem(last=False)
                self._bytes -= old.nbytes

    @property
    def nbytes(self) -> int:
        return self._bytes

    def stats(self) -> str:
        total = self.hits + self.misses
        pct = 100.0 * self.hits / total if total else 0.0
        return (
            f"pano-feature cache: {self.hits}/{total} hits ({pct:.0f}%, "
            f"{self.disk_hits} from disk), {len(self._lru)} entries / "
            f"{self._bytes / 1e6:.0f} MB in memory"
        )
