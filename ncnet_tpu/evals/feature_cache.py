"""Cross-query pano feature cache for the InLoc matching CLI.

The InLoc shortlists repeat panos heavily across the 356 queries, yet the
reference recomputes every pano's backbone features for every query x pano
pair (eval_inloc.py:124-137 — 3,560 forward passes). The backbone is the
largest per-pano device cost (~87 ms of ~300 on v5e, round-2 trace), so a
TPU-first redesign caches pano features ACROSS queries: a hit skips the
pano backbone entirely and dispatches only the correlation/consensus/
extraction half of the step.

Keying and bounds:
  * key = (model_key, pano path, resized (H, W) bucket) — model_key
    identifies the weights (checkpoint path + file mtime, or the init
    seed), so a cache can never serve features from different weights;
    the resize bucket key keeps distinct compilation shapes distinct.
  * bounded host-memory LRU by BYTES (features at the InLoc bucket are
    ~113 MB per pano: 1024ch x 192x144 f32 — backbone_apply returns f32
    even with a bf16 compute dtype; the CLI's default 4 GiB budget holds
    ~36 panos, a 10-pano shortlist window plus reuse locality).
  * optional disk tier (``disk_dir``): entries evicted from memory stay
    on disk (npz keyed by a hash of the key) and promote back on hit —
    sized for re-runs and multi-process sweeps, where the backbone cost
    of the whole pano set is paid at most once per weights.

This module is pure host-side bookkeeping (numpy + files); the caller
owns device placement (jnp.asarray on hit) and extraction (device_get
on store).
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np


def model_cache_key(checkpoint: str, seed: int = 0) -> str:
    """Stable identifier for the weights producing the cached features.

    A checkpoint is identified by its resolved path + params.npz mtime
    (content hashing 100+ MB of weights per CLI start is not worth it;
    an mtime bump after a re-save correctly invalidates). Without a
    checkpoint, features come from the deterministic init -> the seed
    identifies them.
    """
    if checkpoint:
        path = os.path.abspath(os.path.normpath(checkpoint))
        params_file = os.path.join(path, "params.npz")
        try:
            mtime = os.stat(params_file).st_mtime_ns
        except OSError:
            try:
                mtime = os.stat(path).st_mtime_ns
            except OSError:
                mtime = 0
        return f"{path}@{mtime}"
    return f"init-seed-{seed}"


class PanoFeatureCache:
    """Byte-bounded LRU of pano backbone features, optional disk tier."""

    def __init__(self, max_bytes: int, disk_dir: Optional[str] = None,
                 model_key: str = ""):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        self.max_bytes = int(max_bytes)
        self.disk_dir = disk_dir
        self.model_key = model_key
        self._lru: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        # get() runs on the CLI's decode-prefetch thread while put() runs
        # on the main thread; LRU reordering + eviction need the lock.
        self._lock = threading.Lock()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.disk_hits = 0
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)

    def _key(self, pano_path: str, shape: Tuple[int, int]) -> tuple:
        return (self.model_key, pano_path, tuple(shape))

    def _disk_path(self, key: tuple) -> str:
        h = hashlib.sha1(repr(key).encode()).hexdigest()
        return os.path.join(self.disk_dir, f"feat_{h}.npz")

    def get(self, pano_path: str, shape: Tuple[int, int]):
        """Cached features for (pano, resize bucket), or None.

        Disk-tier hits promote back into the memory LRU.
        """
        key = self._key(pano_path, shape)
        with self._lock:
            feats = self._lru.get(key)
            if feats is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                return feats
        if self.disk_dir:
            path = self._disk_path(key)
            if os.path.exists(path):
                import zipfile

                try:
                    with np.load(path) as z:
                        feats = z["feats"]
                except (OSError, ValueError, KeyError, zipfile.BadZipFile):
                    # A partial write (killed run) is a miss, not a crash.
                    feats = None
                if feats is not None:
                    self.hits += 1
                    self.disk_hits += 1
                    self._store_mem(key, feats)
                    return feats
        self.misses += 1
        return None

    def put(self, pano_path: str, shape: Tuple[int, int],
            feats: np.ndarray) -> None:
        key = self._key(pano_path, shape)
        with self._lock:
            if key in self._lru:
                return
        feats = np.asarray(feats)
        if self.disk_dir:
            path = self._disk_path(key)
            if not os.path.exists(path):
                # tmp + rename: a killed run must not leave a truncated
                # npz that later loads as garbage features.
                tmp = path + ".tmp"
                try:
                    # Through a handle: np.savez(str) would append .npz
                    # to the tmp name and the rename would miss it.
                    with open(tmp, "wb") as fh:
                        np.savez(fh, feats=feats)
                    os.replace(tmp, path)
                except OSError:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
        self._store_mem(key, feats)

    def _store_mem(self, key: tuple, feats: np.ndarray) -> None:
        if feats.nbytes > self.max_bytes:
            return  # larger than the whole budget: disk-only (if any)
        with self._lock:
            if key in self._lru:
                return
            self._lru[key] = feats
            self._bytes += feats.nbytes
            while self._bytes > self.max_bytes and len(self._lru) > 1:
                _, old = self._lru.popitem(last=False)
                self._bytes -= old.nbytes

    @property
    def nbytes(self) -> int:
        return self._bytes

    def stats(self) -> str:
        total = self.hits + self.misses
        pct = 100.0 * self.hits / total if total else 0.0
        return (
            f"pano-feature cache: {self.hits}/{total} hits ({pct:.0f}%, "
            f"{self.disk_hits} from disk), {len(self._lru)} entries / "
            f"{self._bytes / 1e6:.0f} MB in memory"
        )
