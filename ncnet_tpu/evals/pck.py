"""PCK (percentage of correct keypoints) metric.

Parity target: lib/eval_util.py:15-55 of the reference (minus its live ipdb
breakpoint at :34, a shipped defect — SURVEY.md §7). Padded keypoints are
marked with -1 in both coordinates; the metric is computed per pair over the
valid prefix and thresholded at alpha * L_pck.

Jit-friendly: instead of the reference's dynamic `:N_pts` slicing (a dynamic
shape), validity is a mask — identical result, static shapes.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..geometry.coords import points_to_unit_coords, points_to_pixel_coords
from ..ops.matches import bilinear_point_transfer


def pck(source_points, warped_points, l_pck, alpha: float = 0.15):
    """Per-pair PCK.

    Args:
      source_points: [b, 2, n] ground-truth source keypoints (pixel coords,
        -1-padded).
      warped_points: [b, 2, n] transferred keypoints.
      l_pck: [b] or [b, 1] reference lengths.
      alpha: threshold fraction (reference default 0.15; the paper reports
        @0.1 — pass explicitly).

    Returns:
      [b] fraction of valid keypoints within alpha * L_pck.
    """
    valid = (source_points[:, 0, :] != -1) & (source_points[:, 1, :] != -1)
    dist = jnp.sqrt(jnp.sum((source_points - warped_points) ** 2, axis=1))
    l_pck = jnp.reshape(l_pck, (-1, 1))
    correct = (dist <= l_pck * alpha) & valid
    n_valid = jnp.maximum(jnp.sum(valid, axis=1), 1)
    return jnp.sum(correct, axis=1) / n_valid


def pck_metric(batch, matches, alpha: float = 0.15):
    """End-to-end keypoint-transfer PCK for a batch.

    Mirrors lib/eval_util.py:30-55: normalize target points, warp through the
    match grid with bilinear interpolation, unnormalize into source pixels,
    and score against the source ground truth.

    Args:
      batch: dict with 'source_points', 'target_points', 'source_im_size',
        'target_im_size', 'L_pck' ([b, ...] jnp arrays).
      matches: (xA, yA, xB, yB) from corr_to_matches.

    Returns:
      [b] PCK values.
    """
    target_norm = points_to_unit_coords(
        batch["target_points"], batch["target_im_size"]
    )
    warped_norm = bilinear_point_transfer(matches, target_norm)
    warped = points_to_pixel_coords(warped_norm, batch["source_im_size"])
    return pck(batch["source_points"], warped, batch["L_pck"], alpha)
