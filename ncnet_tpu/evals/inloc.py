"""InLoc dense-matching outputs for the Matlab localization pipeline.

Parity target: eval_inloc.py:124-221 of the reference — per query x pano:
both-direction match extraction with relocalization, descending score sort,
coordinate-row dedup, recentring onto pixel-cell centers, and a
`matches/<experiment>/<q>.mat` file with the layout the Matlab P3P-RANSAC
stage consumes (lib_matlab/parfor_NC4D_PE_pnponly.m:17-61).

Device/host split: match extraction + sort stay on device; the dedup
(np.unique over coordinate rows) and the .mat write are host-side, matching
where the reference's process boundary to Matlab is (SURVEY.md §3.3).
"""

from __future__ import annotations

import os
import numpy as np
import jax
import jax.numpy as jnp
from scipy.io import savemat

from ..ops.matches import corr_to_matches, relocalize_and_coords
from ..ops.mutual import mutual_matching


def _resolve_extract_impl(impl):
    """'auto' | 'pallas' | 'xla'; None reads NCNET_EXTRACT_IMPL at trace
    time (default 'auto': the Pallas statistics kernel when lowering to
    TPU, the corr_to_matches formulation elsewhere)."""
    if impl is None:
        impl = os.environ.get("NCNET_EXTRACT_IMPL", "auto")
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown extraction impl {impl!r}")
    return impl


def _raw_matches_xla(corr4d, delta4d, k_size, do_softmax):
    """Both directions via corr_to_matches, concatenated [B-dir, A-dir]."""
    a = corr_to_matches(
        corr4d, delta4d=delta4d, k_size=k_size, do_softmax=do_softmax,
        scale="positive", invert_matching_direction=False,
    )
    b = corr_to_matches(
        corr4d, delta4d=delta4d, k_size=k_size, do_softmax=do_softmax,
        scale="positive", invert_matching_direction=True,
    )
    return tuple(jnp.concatenate([u, v], axis=1) for u, v in zip(a, b))


def _raw_matches_stats(
    corr4d, delta4d, k_size, do_softmax, fused_mutual=False, interpret=False
):
    """Both directions from ONE Pallas sweep over the [M, N] matrix.

    The bidirectional statistics kernel (ops.extract_kernel) reads the
    tensor once and yields per-row (per-A) and per-column (per-B)
    max/argmax/sumexp; the softmax score of the max element is exactly
    1 / sumexp (max(softmax(x)) = exp(max - logsumexp)). With
    `fused_mutual`, the final soft mutual-NN filter is applied tile-wise
    inside the kernel (pass 1: bidirectional maxes; pass 2: statistics of
    the filtered values) — the filtered tensor never reaches HBM.
    """
    from ..ops.extract_kernel import (
        bidir_extract_stats_pallas,
        bidir_maxes_pallas,
    )

    shape4d = corr4d.shape[2:]
    fs1, fs2, fs3, fs4 = shape4d
    x2d = corr4d.reshape(fs1 * fs2, fs3 * fs4)
    row_col_max = None
    if fused_mutual:
        row_col_max = bidir_maxes_pallas(x2d, interpret=interpret)
    row, col = bidir_extract_stats_pallas(
        x2d, do_softmax=do_softmax, row_col_max=row_col_max,
        interpret=interpret,
    )

    def direction(stats, probe_n, probe_div, arg_div):
        mx, arg, sumexp = stats
        score = (1.0 / sumexp if do_softmax else mx)[None, :]
        m_i, m_j = (arg // arg_div)[None, :], (arg % arg_div)[None, :]
        pos = jnp.arange(probe_n, dtype=jnp.int32)
        p_i, p_j = (pos // probe_div)[None, :], (pos % probe_div)[None, :]
        return score, m_i, m_j, p_i, p_j

    # Direction False (one match per B position): column statistics.
    s, i_a, j_a, i_b, j_b = direction(col, fs3 * fs4, fs4, fs2)
    d0 = relocalize_and_coords(
        i_a, j_a, i_b, j_b, s, delta4d, k_size, shape4d, "positive"
    )
    # Direction True (one match per A position): row statistics.
    s, i_b, j_b, i_a, j_a = direction(row, fs1 * fs2, fs2, fs4)
    d1 = relocalize_and_coords(
        i_a, j_a, i_b, j_b, s, delta4d, k_size, shape4d, "positive"
    )
    return tuple(jnp.concatenate([u, v], axis=1) for u, v in zip(d0, d1))


def _sort_and_recenter(raw, shape4d, k_size):
    """Shared tail: descending-score device sort + recentring onto
    pixel-cell centers (parity: eval_inloc.py:160-189)."""
    fs1, fs2, fs3, fs4 = shape4d
    xa, ya, xb, yb, score = raw
    order = jnp.argsort(-score[0])
    xa, ya, xb, yb, score = (
        jnp.take(v[0], order) for v in (xa, ya, xb, yb, score)
    )
    k = max(k_size, 1)
    ya = ya * (fs1 * k - 1) / (fs1 * k) + 0.5 / (fs1 * k)
    xa = xa * (fs2 * k - 1) / (fs2 * k) + 0.5 / (fs2 * k)
    yb = yb * (fs3 * k - 1) / (fs3 * k) + 0.5 / (fs3 * k)
    xb = xb * (fs4 * k - 1) / (fs4 * k) + 0.5 / (fs4 * k)
    return xa, ya, xb, yb, score


def inloc_device_matches(
    corr4d,
    delta4d=None,
    k_size: int = 1,
    do_softmax: bool = True,
    both_directions: bool = True,
    invert_direction: bool = False,
    impl=None,
):
    """Device-side match extraction for one pair: jit-safe, no host sync.

    Returns (xA, yA, xB, yB, score) 1-D jnp arrays in 'positive' [0, 1]
    scale, sorted by descending score and recentered to pixel-cell centers.
    Callers jit this together with the model forward so the whole per-pano
    device program is one XLA executable (op-by-op dispatch over a tunneled
    backend costs milliseconds per op).

    `impl` (default: NCNET_EXTRACT_IMPL env, 'auto') picks the extraction
    formulation for the batch-1 both-directions case: 'pallas' = the
    one-read bidirectional statistics kernel, 'xla' = corr_to_matches per
    direction, 'auto' = Pallas when lowering to TPU.
    """
    shape4d = corr4d.shape[2:]
    impl = _resolve_extract_impl(impl)
    fused_ok = both_directions and corr4d.shape[0] == 1 and corr4d.shape[1] == 1

    if impl == "pallas" and not fused_ok:
        raise ValueError(
            "impl='pallas' requires batch 1, a single channel and "
            "both_directions=True (the bidirectional statistics kernel); "
            f"got shape {corr4d.shape}, both_directions={both_directions}"
        )
    if both_directions:
        if impl == "pallas" and fused_ok:
            raw = _raw_matches_stats(corr4d, delta4d, k_size, do_softmax)
        elif impl == "auto" and fused_ok and jax.default_backend() == "tpu":
            # Trace-time backend choice, NOT lax.platform_dependent: the
            # per-platform cond lowers every branch, and the Pallas
            # kernel has no CPU lowering (interpret-only), so the cond
            # itself fails to compile off-TPU.
            raw = _raw_matches_stats(corr4d, delta4d, k_size, do_softmax)
        else:
            raw = _raw_matches_xla(corr4d, delta4d, k_size, do_softmax)
    else:
        raw = corr_to_matches(
            corr4d,
            delta4d=delta4d,
            k_size=k_size,
            do_softmax=do_softmax,
            scale="positive",
            invert_matching_direction=invert_direction,
        )
    return _sort_and_recenter(raw, shape4d, k_size)


def c2f_device_matches(config, params, feat_a, feat_b,
                       do_softmax: bool = True):
    """Coarse-to-fine device-side match extraction for one pair.

    Same return contract as :func:`inloc_device_matches` (both directions,
    'positive' scale, descending-score sort, pixel-cell recentring), so the
    downstream dedup / .mat flow is mode-agnostic. Jit-safe; callers jit it
    together with feature extraction.

    Degenerate knobs (models.ncnet.c2f_is_degenerate) route through the
    one-shot extraction on the stage-1 tensor — bit-identical to the
    one-shot program, relocalization included. On the refined path
    `do_softmax` is ignored: spliced scores are raw filtered-consensus
    values (ops.c2f.splice_matches).
    """
    # Local import: evals must stay importable without pulling the model
    # stack until a c2f caller actually needs it.
    from ..models.ncnet import (
        c2f_coarse_from_features,
        c2f_is_degenerate,
        c2f_raw_matches_from_features,
    )

    if c2f_is_degenerate(config, feat_a.shape, feat_b.shape):
        corr4d, delta4d = c2f_coarse_from_features(
            config, params, feat_a, feat_b
        )
        return inloc_device_matches(
            corr4d, delta4d=delta4d,
            k_size=max(config.relocalization_k_size, 1),
            do_softmax=do_softmax,
        )
    raw = c2f_raw_matches_from_features(
        config, params, feat_a, feat_b, both_directions=True,
        scale="positive",
    )
    fine_shape = (feat_a.shape[2], feat_a.shape[3],
                  feat_b.shape[2], feat_b.shape[3])
    return _sort_and_recenter(raw, fine_shape, 1)


def inloc_matches_from_consensus(
    consensus4d,
    delta4d=None,
    k_size: int = 1,
    do_softmax: bool = True,
    impl=None,
    interpret: bool = False,
):
    """Fused final-MutualMatching + both-direction extraction.

    Takes the CONSENSUS output (match_pipeline(..., final_mutual=False),
    still in the storage dtype) and evaluates the last soft mutual-NN
    filter inside the extraction kernel: pass 1 reads the tensor once for
    its bidirectional maxes, pass 2 filters each tile in VMEM and takes
    match statistics — the filtered tensor never materializes in HBM, and
    the tensor moves through HBM twice (bf16) instead of the unfused
    four+ full-tensor round trips (mutual write + extraction reads).

    Same return contract as `inloc_device_matches`.
    """
    if consensus4d.shape[0] != 1 or consensus4d.shape[1] != 1:
        raise ValueError("fused mutual+extraction requires batch 1")
    shape4d = consensus4d.shape[2:]
    impl = _resolve_extract_impl(impl)

    def fused(c):
        return _raw_matches_stats(
            c, delta4d, k_size, do_softmax, fused_mutual=True,
            interpret=interpret,
        )

    def unfused(c):
        # Bit-parity with the default pipeline tail: mutual filter in the
        # storage dtype, then f32 extraction.
        filtered = mutual_matching(c).astype(jnp.float32)
        return _raw_matches_xla(filtered, delta4d, k_size, do_softmax)

    if impl == "pallas":
        raw = fused(consensus4d)
    elif impl == "xla":
        raw = unfused(consensus4d)
    elif jax.default_backend() == "tpu":
        # Trace-time backend choice (see inloc_device_matches): the
        # platform cond would lower the interpret-only Pallas branch on
        # CPU and fail the whole compile.
        raw = fused(consensus4d)
    else:
        raw = unfused(consensus4d)
    return _sort_and_recenter(raw, shape4d, k_size)


def dedup_matches(xa, ya, xb, yb, score):
    """Host-side dedup of coordinate rows (parity: eval_inloc.py:160-173).

    Expects descending-score-sorted inputs; np.unique keeps the first = best
    occurrence index per unique coordinate row.

    The returned order is CANONICAL, tied scores included: descending
    score, ties broken by the lexicographic coordinate row, then by the
    original (stable) index. The upstream device sort only orders by
    score, so rows sharing a score can arrive in any permutation
    (extraction impl, direction-concat order); without a deterministic
    tiebreak here, two runs over the same pair produce tables that are
    equal as sets but not bitwise — which breaks the content-addressed
    result cache and the shadow comparator's rung-0 bitwise contract.
    """
    coords = np.stack(
        [np.asarray(xa), np.asarray(ya), np.asarray(xb), np.asarray(yb)], axis=0
    )
    _, unique_idx = np.unique(coords, axis=1, return_index=True)
    unique_idx = np.sort(unique_idx)
    uscore = np.asarray(score)[unique_idx]
    sub = coords[:, unique_idx]
    # np.lexsort keys run minor-to-major: primary -score (descending),
    # then xa, ya, xb, yb, then the surviving input index.
    order = np.lexsort(
        (unique_idx, sub[3], sub[2], sub[1], sub[0], -uscore)
    )
    keep = unique_idx[order]
    return (
        coords[0, keep],
        coords[1, keep],
        coords[2, keep],
        coords[3, keep],
        uscore[order],
    )


def extract_inloc_matches(
    corr4d,
    delta4d=None,
    k_size: int = 1,
    do_softmax: bool = True,
    both_directions: bool = True,
    invert_direction: bool = False,
):
    """Extract, merge and dedup matches for one image pair.

    Convenience composition of `inloc_device_matches` (device) and
    `dedup_matches` (host): (xA, yA, xB, yB, score) 1-D float arrays,
    recentered, descending-score-sorted, duplicate coordinate rows removed.
    """
    return dedup_matches(
        *inloc_device_matches(
            corr4d,
            delta4d=delta4d,
            k_size=k_size,
            do_softmax=do_softmax,
            both_directions=both_directions,
            invert_direction=invert_direction,
        )
    )


def write_matches_mat(
    path: str,
    all_matches: np.ndarray,
    query_fn: str,
    pano_fn_all,
):
    """Write the per-query .mat file (layout parity: eval_inloc.py:221)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    savemat(
        path,
        {"matches": all_matches, "query_fn": query_fn, "pano_fn": pano_fn_all},
        do_compression=True,
    )


def matches_buffer(n_panos: int, n_matches: int) -> np.ndarray:
    """Allocate the [1, n_panos, N, 5] buffer (parity: eval_inloc.py:126)."""
    return np.zeros((1, n_panos, n_matches, 5))


def fill_matches(buffer: np.ndarray, pano_idx: int, match_tuple):
    """Store one pano's matches into the buffer rows (xA,yA,xB,yB,score)."""
    xa, ya, xb, yb, score = match_tuple
    n = min(len(xa), buffer.shape[2])
    buffer[0, pano_idx, :n, 0] = xa[:n]
    buffer[0, pano_idx, :n, 1] = ya[:n]
    buffer[0, pano_idx, :n, 2] = xb[:n]
    buffer[0, pano_idx, :n, 3] = yb[:n]
    buffer[0, pano_idx, :n, 4] = score[:n]
    return buffer
