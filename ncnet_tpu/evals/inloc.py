"""InLoc dense-matching outputs for the Matlab localization pipeline.

Parity target: eval_inloc.py:124-221 of the reference — per query x pano:
both-direction match extraction with relocalization, descending score sort,
coordinate-row dedup, recentring onto pixel-cell centers, and a
`matches/<experiment>/<q>.mat` file with the layout the Matlab P3P-RANSAC
stage consumes (lib_matlab/parfor_NC4D_PE_pnponly.m:17-61).

Device/host split: match extraction + sort stay on device; the dedup
(np.unique over coordinate rows) and the .mat write are host-side, matching
where the reference's process boundary to Matlab is (SURVEY.md §3.3).
"""

from __future__ import annotations

import os
import numpy as np
import jax.numpy as jnp
from scipy.io import savemat

from ..ops.matches import corr_to_matches


def inloc_device_matches(
    corr4d,
    delta4d=None,
    k_size: int = 1,
    do_softmax: bool = True,
    both_directions: bool = True,
    invert_direction: bool = False,
):
    """Device-side match extraction for one pair: jit-safe, no host sync.

    Returns (xA, yA, xB, yB, score) 1-D jnp arrays in 'positive' [0, 1]
    scale, sorted by descending score and recentered to pixel-cell centers.
    Callers jit this together with the model forward so the whole per-pano
    device program is one XLA executable (op-by-op dispatch over a tunneled
    backend costs milliseconds per op).
    """
    fs1, fs2, fs3, fs4 = corr4d.shape[2:]

    def one_direction(invert):
        return corr_to_matches(
            corr4d,
            delta4d=delta4d,
            k_size=k_size,
            do_softmax=do_softmax,
            scale="positive",
            invert_matching_direction=invert,
        )

    if both_directions:
        a = one_direction(False)
        b = one_direction(True)
        xa, ya, xb, yb, score = (
            jnp.concatenate([u, v], axis=1) for u, v in zip(a, b)
        )
    else:
        xa, ya, xb, yb, score = one_direction(invert_direction)

    # Descending score sort on device (keeps the max-score duplicate first).
    order = jnp.argsort(-score[0])
    xa, ya, xb, yb, score = (
        jnp.take(v[0], order) for v in (xa, ya, xb, yb, score)
    )

    # Recenter normalized [0,1] coords onto pixel-cell centers
    # (parity: eval_inloc.py:179-189).
    k = max(k_size, 1)
    ya = ya * (fs1 * k - 1) / (fs1 * k) + 0.5 / (fs1 * k)
    xa = xa * (fs2 * k - 1) / (fs2 * k) + 0.5 / (fs2 * k)
    yb = yb * (fs3 * k - 1) / (fs3 * k) + 0.5 / (fs3 * k)
    xb = xb * (fs4 * k - 1) / (fs4 * k) + 0.5 / (fs4 * k)
    return xa, ya, xb, yb, score


def dedup_matches(xa, ya, xb, yb, score):
    """Host-side dedup of coordinate rows (parity: eval_inloc.py:160-173).

    Expects descending-score-sorted inputs; np.unique keeps the first = best
    occurrence index per unique coordinate row.
    """
    coords = np.stack(
        [np.asarray(xa), np.asarray(ya), np.asarray(xb), np.asarray(yb)], axis=0
    )
    _, unique_idx = np.unique(coords, axis=1, return_index=True)
    unique_idx = np.sort(unique_idx)
    return (
        coords[0, unique_idx],
        coords[1, unique_idx],
        coords[2, unique_idx],
        coords[3, unique_idx],
        np.asarray(score)[unique_idx],
    )


def extract_inloc_matches(
    corr4d,
    delta4d=None,
    k_size: int = 1,
    do_softmax: bool = True,
    both_directions: bool = True,
    invert_direction: bool = False,
):
    """Extract, merge and dedup matches for one image pair.

    Convenience composition of `inloc_device_matches` (device) and
    `dedup_matches` (host): (xA, yA, xB, yB, score) 1-D float arrays,
    recentered, descending-score-sorted, duplicate coordinate rows removed.
    """
    return dedup_matches(
        *inloc_device_matches(
            corr4d,
            delta4d=delta4d,
            k_size=k_size,
            do_softmax=do_softmax,
            both_directions=both_directions,
            invert_direction=invert_direction,
        )
    )


def write_matches_mat(
    path: str,
    all_matches: np.ndarray,
    query_fn: str,
    pano_fn_all,
):
    """Write the per-query .mat file (layout parity: eval_inloc.py:221)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    savemat(
        path,
        {"matches": all_matches, "query_fn": query_fn, "pano_fn": pano_fn_all},
        do_compression=True,
    )


def matches_buffer(n_panos: int, n_matches: int) -> np.ndarray:
    """Allocate the [1, n_panos, N, 5] buffer (parity: eval_inloc.py:126)."""
    return np.zeros((1, n_panos, n_matches, 5))


def fill_matches(buffer: np.ndarray, pano_idx: int, match_tuple):
    """Store one pano's matches into the buffer rows (xA,yA,xB,yB,score)."""
    xa, ya, xb, yb, score = match_tuple
    n = min(len(xa), buffer.shape[2])
    buffer[0, pano_idx, :n, 0] = xa[:n]
    buffer[0, pano_idx, :n, 1] = ya[:n]
    buffer[0, pano_idx, :n, 2] = xb[:n]
    buffer[0, pano_idx, :n, 3] = yb[:n]
    buffer[0, pano_idx, :n, 4] = score[:n]
    return buffer
