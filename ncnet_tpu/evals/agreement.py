"""Shared match-quality comparison: one routine for both quality gates.

The offline parity gate (``tools/real_parity.py``) and the online
shadow comparator (``ncnet_tpu/serving/shadow.py``) both answer the
same question — "do two match results agree within a pixel tolerance?"
— and MUST keep answering it the same way, or the production quality
numbers drift apart from the numbers the parity gate was calibrated
on. This module is the single home for that math:

* ``within_tolerance`` / ``delta_within_gate`` — the scalar gates
  real_parity applies to PCK values and A/B deltas.
* ``match_table_agreement`` — agreement@τ px between two serving match
  tables (the ``[n, 5]`` ``(xa, ya, xb, yb, score)`` rows
  ``serving/engine.py`` returns): the thresholded-distance criterion is
  the same "endpoint within τ of reference" rule PCK uses
  (``evals/pck.py``), applied per source keypoint instead of per
  annotated keypoint.
* ``mutual_nn_fraction`` — forward↔backward mutual-nearest-neighbour
  agreement recovered host-side from a merged match table (the engine
  concatenates both probe directions before dedup, so both maps are
  present in the one table).

Everything here is plain numpy on host arrays — it runs in the serving
hot path's host tail and in offline tools, never under jit.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "within_tolerance",
    "delta_within_gate",
    "match_table_agreement",
    "mutual_nn_fraction",
]

#: The report-only A/B gate width real_parity applies to c2f and
#: session PCK deltas (docs/PERF.md: within 1 PCK point of baseline).
DELTA_GATE = 0.01


def within_tolerance(value, expected, tolerance):
    """The parity gate: |value - expected| <= tolerance."""
    return bool(abs(float(value) - float(expected)) <= float(tolerance))


def delta_within_gate(delta, gate=DELTA_GATE):
    """The A/B delta gate: |delta| <= gate (default ±0.01 PCK)."""
    return bool(abs(float(delta)) <= float(gate))


def _best_by_source(rows):
    """Highest-score target per source coordinate.

    Returns ``{(xa, ya): (xb, yb)}`` keeping the best-scoring row per
    source point — the same keep-first-best-after-sort convention
    ``evals/inloc.dedup_matches`` applies to whole rows.
    """
    if rows is None or len(rows) == 0:
        return {}
    rows = np.asarray(rows, dtype=np.float32)
    order = np.argsort(-rows[:, 4], kind="stable")
    best = {}
    for i in order:
        key = (float(rows[i, 0]), float(rows[i, 1]))
        if key not in best:
            best[key] = (float(rows[i, 2]), float(rows[i, 3]))
    return best


def match_table_agreement(ref_rows, cand_rows, tau_px=2.0):
    """Agreement@τ px between two ``[n, 5]`` serving match tables.

    ``ref_rows`` is the trusted result (rung 0 / unseeded shadow
    re-run), ``cand_rows`` the one under test (the degraded response).
    Per source point present in BOTH tables, the candidate agrees when
    its best-scoring target endpoint lies within ``tau_px`` (Euclidean)
    of the reference's — PCK's thresholded-distance criterion with the
    reference table standing in for ground truth.

    Returns a dict::

        agreement  fraction of compared source points within tau_px
                   (1.0 when both tables are empty)
        compared   source points present in both tables
        coverage   compared / reference source points
        n_ref, n_cand   raw row counts
        bitwise    np.array_equal over the full tables — the exactness
                   control the rung-0 shadow samples must pass
        tau_px     the tolerance used
    """
    ref = np.asarray(ref_rows, dtype=np.float32) if ref_rows is not None \
        else np.zeros((0, 5), np.float32)
    cand = np.asarray(cand_rows, dtype=np.float32) if cand_rows is not None \
        else np.zeros((0, 5), np.float32)
    ref_best = _best_by_source(ref)
    cand_best = _best_by_source(cand)
    shared = [k for k in ref_best if k in cand_best]
    agree = 0
    for key in shared:
        rx, ry = ref_best[key]
        cx, cy = cand_best[key]
        if float(np.hypot(rx - cx, ry - cy)) <= float(tau_px):
            agree += 1
    if shared:
        agreement = agree / len(shared)
    else:
        # No overlap to compare: identical emptiness is agreement,
        # anything else is a miss.
        agreement = 1.0 if (not ref_best and not cand_best) else 0.0
    return {
        "agreement": float(agreement),
        "compared": int(len(shared)),
        "coverage": float(len(shared) / len(ref_best)) if ref_best else 1.0,
        "n_ref": int(ref.shape[0]),
        "n_cand": int(cand.shape[0]),
        "bitwise": bool(ref.shape == cand.shape and np.array_equal(ref,
                                                                   cand)),
        "tau_px": float(tau_px),
    }


def mutual_nn_fraction(rows):
    """Forward↔backward mutual-NN agreement from one merged table.

    The engine's match table concatenates both probe directions (per-B
    and per-A) before dedup, so it holds both the forward map
    source→target and the backward map target→source. A source point is
    *mutual* when its best target's own best source points back at it
    (exact coordinate round-trip — the soft mutual-NN filter's hard
    counterpart, computable host-side with no device work).

    Returns the mutual fraction over forward entries (0.0 for an empty
    table).
    """
    if rows is None or len(rows) == 0:
        return 0.0
    forward = _best_by_source(rows)
    if not forward:
        return 0.0
    rows = np.asarray(rows, dtype=np.float32)
    # Backward best: highest-score source per target coordinate.
    order = np.argsort(-rows[:, 4], kind="stable")
    backward = {}
    for i in order:
        key = (float(rows[i, 2]), float(rows[i, 3]))
        if key not in backward:
            backward[key] = (float(rows[i, 0]), float(rows[i, 1]))
    mutual = sum(1 for src, tgt in forward.items()
                 if backward.get(tgt) == src)
    return float(mutual / len(forward))
