"""Dense-flow evaluation output for TSS.

Parity target: lib/eval_util.py:58-100 — for every pixel of the target image,
warp its normalized coords through the match grid and write the resulting
target->source displacement field as a Middlebury .flo file consumed by the
external TSS evaluation kit (out-of-bounds pixels get the 1e10 sentinel).

The per-pixel warp runs on device as one batched bilinear interpolation over
the match grid (the reference loops in python per batch element).
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from ..geometry.flow_io import sampling_grid_to_flow, write_flo_file
from ..ops.matches import bilinear_point_transfer


def dense_warp_grid(matches, h_tgt: int, w_tgt: int):
    """Warp every target pixel through the match grid.

    Returns [1, h_tgt, w_tgt, 2] normalized source coords.
    """
    xs = jnp.linspace(-1.0, 1.0, w_tgt)
    ys = jnp.linspace(-1.0, 1.0, h_tgt)
    gx, gy = jnp.meshgrid(xs, ys)
    pts = jnp.stack([gx.reshape(-1), gy.reshape(-1)], axis=0)[None]  # [1,2,HW]
    warped = bilinear_point_transfer(matches, pts)  # [1, 2, HW]
    return jnp.transpose(warped, (0, 2, 1)).reshape(1, h_tgt, w_tgt, 2)


def write_flow_output(
    matches,
    source_im_size,
    target_im_size,
    flow_rel_path: str,
    output_dir: str,
):
    """Compute the dense flow for one pair and write `<output_dir>/nc/<rel>`."""
    h_src, w_src = int(source_im_size[0]), int(source_im_size[1])
    h_tgt, w_tgt = int(target_im_size[0]), int(target_im_size[1])
    grid = np.asarray(dense_warp_grid(matches, h_tgt, w_tgt))
    flow = sampling_grid_to_flow(grid, h_src, w_src)
    out_path = os.path.join(output_dir, "nc", flow_rel_path)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    write_flo_file(flow, out_path)
    return out_path
