"""Crash-safe resumable bulk map of the matcher over a pair manifest.

The contract is **exactly-once**: a ``kill -9`` at ANY point — mid
dispatch, mid ledger append, between a checkpoint's tmp write and its
rename — resumes with zero lost and zero duplicated results, and an
interrupted-then-resumed run's ledger is *byte-identical* to an
uninterrupted one's (tests/test_bulk_crash_e2e.py proves this with
real SIGKILLs). The machinery:

* **Ledger** (``ledger.jsonl``): append-only canonical-JSON lines, one
  per pair, written strictly in row order (a reorder buffer holds
  results that finish out of order). Each commit is flushed *and
  fsynced* before the cursor may advance, so the only possible damage
  from a crash is one torn trailing line — which recovery truncates.
* **Checkpoint** (``checkpoint.json``): the shard cursor, written
  tmp + fsync + atomic ``os.replace`` (the ``evals/feature_cache.py``
  idiom). It pins the manifest digest, so resuming against an edited
  manifest is refused instead of silently mismatching rows. The
  checkpoint is an *optimization*: recovery re-scans the ledger tail
  past it, so a checkpoint lost mid-rename costs re-counting, never
  correctness.
* **Quarantine** (``quarantine.jsonl``): poison pairs — those that
  keep failing even after the batcher's bisection isolates them, until
  their retry schedule exhausts — land here with their failure record
  instead of aborting the run. The pair's ledger line says
  ``"status": "quarantined"``; the sidecar carries the diagnosis (and,
  being appended before the ledger line commits, may hold duplicates
  after a crash — the ledger is the exactly-once record).
* **Lock** (``.bulk.lock``): an exclusive ``flock`` so two resumes
  cannot interleave appends into one ledger.

Failure handling composes the whole reliability layer: per-pair
:class:`~ncnet_tpu.reliability.retry.RetryPolicy` sessions draw on one
shared :class:`~ncnet_tpu.reliability.retry.RetryBudget`; fleet
backpressure (``RejectedError``) re-queues without spending attempts;
replica death is absorbed upstream by ``FleetDispatcher`` re-routing.
Chaos hooks: ``bulk.read`` / ``bulk.dispatch`` / ``bulk.commit`` /
``bulk.checkpoint`` failpoints (docs/RELIABILITY.md), ``bulk.*``
metrics (docs/OBSERVABILITY.md), and flat ``bulk.commit`` /
``bulk.shard`` trace spans.

The driver is engine-agnostic: ``prepare(PairRow) -> (bucket_key,
payload)`` and ``submit(bucket_key, payload) -> Future`` are whatever
the caller wires — a real ``MatchFleet`` dispatcher, the jax-free
:mod:`~ncnet_tpu.pipeline.echo` fleet, or a bare test stub.
"""

from __future__ import annotations

import csv
import glob
import hashlib
import heapq
import json
import os
import queue
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from .. import obs
from ..obs import trace
from ..reliability import failpoints
from ..reliability.retry import RetryBudget, RetryPolicy
from ..serving.batcher import PoisonRequestError, RejectedError

LEDGER_NAME = "ledger.jsonl"
CHECKPOINT_NAME = "checkpoint.json"
QUARANTINE_NAME = "quarantine.jsonl"
LOCK_NAME = ".bulk.lock"

#: Permanent per-pair input errors: retrying cannot help, quarantine
#: immediately (a missing/corrupt image stays missing).
_BAD_INPUT = (ValueError, TypeError, KeyError, FileNotFoundError)


class LedgerError(RuntimeError):
    """The out_dir's ledger state is unusable (concurrent writer,
    manifest mismatch, corrupt non-tail ledger line)."""


@dataclass
class PairRow:
    """One manifest row: a (query, pano) pair plus caller context."""

    row: int          # 0-based manifest position — the resume key
    pair_id: str
    query: str
    pano: str
    extra: dict = field(default_factory=dict)


def manifest_digest(path: str) -> str:
    """Content digest pinning a ledger to the manifest that built it."""
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def iter_manifest(path: str) -> Iterator[PairRow]:
    """Stream PairRows from a CSV (header: query,pano[,id]) or JSONL
    (``{"query":..., "pano":..., "id":...}``) manifest. Never loads the
    file — million-row manifests stream at O(1) memory. Extra columns /
    keys ride along in ``PairRow.extra``.
    """
    if path.endswith(".csv"):
        with open(path, newline="") as fh:
            reader = csv.DictReader(fh)
            for n, rec in enumerate(reader):
                yield _pair_row(n, rec, path)
        return
    with open(path) as fh:
        n = 0
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                raise LedgerError(
                    f"bad manifest line {n} in {path}: {exc}") from exc
            yield _pair_row(n, rec, path)
            n += 1


def _pair_row(n: int, rec: dict, path: str) -> PairRow:
    try:
        query, pano = rec["query"], rec["pano"]
    except KeyError as exc:
        raise LedgerError(
            f"manifest row {n} in {path} missing {exc} "
            "(need query,pano[,id])") from exc
    if not query or not pano:
        raise LedgerError(f"manifest row {n} in {path}: empty query/pano")
    pair_id = rec.get("id") or f"pair-{n:08d}"
    extra = {k: v for k, v in rec.items()
             if k not in ("query", "pano", "id") and v not in (None, "")}
    return PairRow(row=n, pair_id=str(pair_id), query=str(query),
                   pano=str(pano), extra=extra)


def canonical_line(rec: dict) -> str:
    """The ledger's byte format: sorted keys, no whitespace, one line.
    Determinism here is what makes resumed runs byte-identical."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":")) + "\n"


class BulkLedger:
    """Crash-safe exactly-once progress journal for one bulk run.

    Layout under ``out_dir``: ``ledger.jsonl`` (results, row-ordered),
    ``checkpoint.json`` (cursor), ``quarantine.jsonl`` (poison
    diagnoses), ``.bulk.lock`` (single-writer flock). See the module
    docstring for the recovery protocol.
    """

    def __init__(self, out_dir: str, manifest_sha: str):
        self.out_dir = out_dir
        self.manifest_sha = manifest_sha
        os.makedirs(out_dir, exist_ok=True)
        self.ledger_path = os.path.join(out_dir, LEDGER_NAME)
        self.checkpoint_path = os.path.join(out_dir, CHECKPOINT_NAME)
        self.quarantine_path = os.path.join(out_dir, QUARANTINE_NAME)
        self.next_row = 0
        self.resumes = 0
        self.truncated_tail = False
        self._lfh = None
        self._qfh = None
        self._lock_fh = open(os.path.join(out_dir, LOCK_NAME), "a+")
        try:
            import fcntl

            try:
                fcntl.flock(self._lock_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                self._lock_fh.close()
                raise LedgerError(
                    f"another bulk run holds {out_dir!r} "
                    "(exclusive .bulk.lock)") from None
        except ImportError:  # non-posix: no advisory locking
            pass

    # -- recovery ---------------------------------------------------------

    def recover(self) -> int:
        """Rebuild the cursor from disk; returns the first undone row.

        Order of trust: the ledger is authoritative, the checkpoint is
        a scan hint. Recovery (1) drops orphan checkpoint tmps from a
        crash mid-write, (2) validates the checkpoint's manifest pin,
        (3) scans ledger lines from the checkpointed byte offset
        verifying rows are consecutive, (4) truncates a torn trailing
        line (the only damage an fsync-per-commit ledger can take), and
        (5) persists a fresh checkpoint so the recovered state is
        itself durable before any new work commits.
        """
        for tmp in glob.glob(self.checkpoint_path + ".*.tmp"):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        ck = None
        if os.path.exists(self.checkpoint_path):
            try:
                with open(self.checkpoint_path) as fh:
                    ck = json.load(fh)
            except (OSError, json.JSONDecodeError) as exc:
                raise LedgerError(
                    f"corrupt checkpoint {self.checkpoint_path}: {exc}"
                ) from exc
            if ck.get("manifest_sha256") != self.manifest_sha:
                raise LedgerError(
                    "manifest changed since this ledger was started "
                    f"(checkpoint pins {ck.get('manifest_sha256')!r}); "
                    "bulk resume requires the identical manifest")
        base_bytes = int(ck["ledger_bytes"]) if ck else 0
        self.next_row = int(ck["next_row"]) if ck else 0
        prior_resumes = int(ck.get("resumes", 0)) if ck else 0
        had_state = ck is not None or os.path.exists(self.ledger_path)
        if os.path.exists(self.ledger_path):
            self._scan_tail(base_bytes)
        elif base_bytes:
            raise LedgerError("checkpoint present but ledger.jsonl missing")
        self._truncate_torn(self.quarantine_path)
        self.resumes = prior_resumes + (1 if had_state else 0)
        self._lfh = open(self.ledger_path, "ab")
        self._qfh = open(self.quarantine_path, "ab")
        # Durable immediately: the very first commit of this run already
        # has a checkpoint carrying the manifest pin behind it.
        self.write_checkpoint()
        if had_state:
            obs.counter("bulk.resumes").inc()
        return self.next_row

    def _scan_tail(self, base_bytes: int) -> None:
        size = os.path.getsize(self.ledger_path)
        if size < base_bytes:
            raise LedgerError(
                f"ledger shorter ({size}B) than its checkpoint claims "
                f"({base_bytes}B) — the ledger was edited or truncated")
        with open(self.ledger_path, "rb+") as fh:
            fh.seek(base_bytes)
            data = fh.read()
            good = data.rfind(b"\n") + 1
            expect = self.next_row
            for line in data[:good].splitlines():
                try:
                    rec = json.loads(line)
                    row = int(rec["row"])
                except (ValueError, KeyError) as exc:
                    raise LedgerError(
                        f"corrupt ledger line at row {expect}: {exc}"
                    ) from exc
                if row != expect:
                    raise LedgerError(
                        f"ledger rows not consecutive: saw {row}, "
                        f"expected {expect}")
                expect += 1
            if good < len(data):
                # Torn tail: the crash interrupted an append mid-line.
                # The row it carried was never acked, so dropping it
                # loses nothing — the resume recomputes it.
                fh.truncate(base_bytes + good)
                self.truncated_tail = True
            self.next_row = expect

    def _truncate_torn(self, path: str) -> None:
        """Drop a torn (newline-less) trailing line from an append log."""
        if not os.path.exists(path):
            return
        with open(path, "rb+") as fh:
            data = fh.read()
            good = data.rfind(b"\n") + 1
            if good < len(data):
                fh.truncate(good)
                self.truncated_tail = True

    # -- writes -----------------------------------------------------------

    def commit(self, records: List[dict]) -> None:
        """Append a contiguous run of row-ordered records, durably.

        ``records[i]["row"]`` must continue ``next_row`` exactly — the
        driver's reorder buffer guarantees it; anything else is a bug
        worth dying loudly for. The ``bulk.commit`` failpoint fires
        before any byte is written: a kill there loses only un-acked
        work, which the resume redoes.
        """
        for i, rec in enumerate(records):
            if rec.get("row") != self.next_row + i:
                raise LedgerError(
                    f"commit out of order: record {i} has row "
                    f"{rec.get('row')}, ledger expects {self.next_row + i}")
        failpoints.fire("bulk.commit", payload=self.next_row)
        t0 = time.monotonic()
        buf = "".join(canonical_line(r) for r in records).encode()
        self._lfh.write(buf)
        self._lfh.flush()
        os.fsync(self._lfh.fileno())
        self.next_row += len(records)
        obs.counter("bulk.commits").inc()
        trace.emit_span("bulk.commit", time.monotonic() - t0,
                        rows=len(records))

    def quarantine(self, record: dict) -> None:
        """Durably append one poison diagnosis to the sidecar. Called
        *before* the pair's ledger line commits, so a crash in between
        can duplicate a sidecar entry but never lose one."""
        self._qfh.write(canonical_line(record).encode())
        self._qfh.flush()
        os.fsync(self._qfh.fileno())
        obs.counter("bulk.quarantined").inc()
        obs.event("bulk_quarantine", **record)

    def write_checkpoint(self) -> None:
        """Atomically persist the cursor: tmp + fsync + rename.

        The ``bulk.checkpoint`` failpoint sits exactly between the
        fsynced tmp write and the ``os.replace`` — the nastiest window,
        where a crash leaves a complete orphan tmp beside a stale live
        checkpoint. Recovery deletes the orphan and re-scans from the
        stale cursor; nothing is lost either way.
        """
        self._lfh.flush()
        rec = {
            "version": 1,
            "manifest_sha256": self.manifest_sha,
            "next_row": self.next_row,
            "ledger_bytes": self._lfh.tell(),
            "resumes": self.resumes,
        }
        tmp = f"{self.checkpoint_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            fh.write(canonical_line(rec))
            fh.flush()
            os.fsync(fh.fileno())
        failpoints.fire("bulk.checkpoint", payload=self.next_row)
        os.replace(tmp, self.checkpoint_path)
        try:  # directory fsync: make the rename itself power-durable
            dfd = os.open(self.out_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        obs.counter("bulk.checkpoints").inc()

    def ledger_rows(self) -> Iterator[dict]:
        """Stream committed ledger records (verification / reporting)."""
        if not os.path.exists(self.ledger_path):
            return
        with open(self.ledger_path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    yield json.loads(line)

    def close(self) -> None:
        for fh in (self._lfh, self._qfh):
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
        self._lfh = self._qfh = None
        try:
            self._lock_fh.close()  # closing drops the flock
        except OSError:
            pass


# -- result records -------------------------------------------------------


def default_record(pair: PairRow, result: Any) -> dict:
    """Ledger record for one matched pair: id + match digest.

    Deliberately free of timing, attempt counts, and replica ids —
    anything nondeterministic would break the byte-identical-resume
    guarantee. The matches themselves are digested, not stored: a
    million-pair ledger stays grep-able, and the digest still catches
    any resume that recomputes a different answer.
    """
    matches = result.get("matches") if isinstance(result, dict) else result
    if hasattr(matches, "tobytes"):
        blob = matches.tobytes()
    elif isinstance(matches, (bytes, bytearray)):
        blob = bytes(matches)
    elif isinstance(matches, str):
        blob = matches.encode()
    else:
        blob = json.dumps(matches, sort_keys=True, default=str).encode()
    n = result.get("n_matches") if isinstance(result, dict) else None
    if n is None:
        n = getattr(matches, "shape", (0,))[0] if matches is not None else 0
    return {
        "id": pair.pair_id,
        "n_matches": int(n),
        "row": pair.row,
        "sha256": hashlib.sha256(blob).hexdigest(),
        "status": "ok",
    }


def _quarantine_ledger_record(pair: PairRow, kind: str, error: str) -> dict:
    return {
        "error": error[:200],
        "id": pair.pair_id,
        "kind": kind,
        "row": pair.row,
        "status": "quarantined",
    }


# -- the driver -----------------------------------------------------------


@dataclass
class _Flight:
    """One in-flight pair: its prepared payload + retry state."""

    pair: PairRow
    session: Any  # RetrySession
    bucket_key: Any = None
    payload: Any = None
    attempts: int = 0
    #: Per-pair trace context (obs/trace.py): minted at flight
    #: creation, attached around every dispatch so the batcher/
    #: dispatcher spans (and, through a MatchClient-backed submit, the
    #: wire header) parent onto ONE ``bulk.pair`` root per manifest
    #: row — retries and redispatch hops included. ``t0`` is the
    #: flight-creation clock the root's duration is measured from.
    ctx: Any = ()
    t0: float = 0.0


def run_bulk(
    manifest: str,
    out_dir: str,
    prepare: Callable[[PairRow], Tuple[Any, Any]],
    submit: Callable[[Any, Any], Any],
    *,
    shard_size: int = 512,
    max_inflight: int = 32,
    checkpoint_every: int = 64,
    retry_policy: Optional[RetryPolicy] = None,
    record_fn: Callable[[PairRow, Any], dict] = default_record,
    drive: Optional[Callable[[], None]] = None,
    clock: Callable[[], float] = time.monotonic,
    poll_s: float = 0.05,
    total_rows: Optional[int] = None,
) -> dict:
    """Map ``submit`` over every manifest row, exactly once, resumably.

    Keeps up to ``max_inflight`` pairs in the fleet at a time; results
    may complete in any order (retries, multi-replica routing) but
    commit strictly in row order through a reorder buffer. A shard is
    ``shard_size`` consecutive rows — purely a checkpoint/progress
    granule (``bulk.shards_done``), forced-checkpointed at its
    boundary; within a shard the cursor also checkpoints every
    ``checkpoint_every`` committed rows, bounding redo-after-crash.

    ``drive`` is the threadless test hook: when set, the loop calls it
    instead of blocking on the completion queue (fake-clock suites pump
    replica ``poll()`` there). ``submit`` must return a Future whose
    result carries the BatchResult contract (``.result`` attribute) or
    the raw engine result dict.
    """
    if retry_policy is None:
        retry_policy = RetryPolicy(
            max_attempts=4, base_delay_s=0.05, max_delay_s=2.0,
            budget=RetryBudget(capacity=50.0, refill_per_success=0.5),
            clock=clock,
        )
    shard_size = max(1, int(shard_size))
    checkpoint_every = max(1, int(checkpoint_every))
    max_inflight = max(1, int(max_inflight))

    ledger = BulkLedger(out_dir, manifest_digest(manifest))
    t_start = clock()
    start_row = ledger.recover()
    source = (p for p in iter_manifest(manifest) if p.row >= start_row)
    if total_rows is not None:
        obs.gauge("bulk.pairs_total").set(int(total_rows))

    inflight: Dict[int, _Flight] = {}
    ready: Dict[int, dict] = {}      # reorder buffer: row -> ledger record
    doneq: "queue.Queue" = queue.Queue()
    retries: List[Tuple[float, int, int]] = []  # (due, seq, row) heap
    seq = 0
    exhausted = False
    since_checkpoint = 0
    quarantined = retried = 0
    shard_t0: Dict[int, float] = {}  # shard index -> first-launch clock

    def _finish(row: int, record: dict) -> None:
        fl = inflight.pop(row, None)
        ready[row] = record
        if fl is not None and fl.ctx:
            # Close the pair's trace root: one span per manifest row,
            # however many retries/requeues it took to settle.
            trace.emit_root(
                fl.ctx[0], "bulk.pair", max(0.0, clock() - fl.t0),
                row=row, attempts=fl.attempts or 1,
                status=record.get("status"))

    def _quarantine(fl: _Flight, kind: str, exc: BaseException) -> None:
        nonlocal quarantined
        err = f"{type(exc).__name__}: {exc}"
        ledger.quarantine({
            "attempts": fl.attempts or 1,
            "error": err,
            "id": fl.pair.pair_id,
            "kind": kind,
            "query": fl.pair.query,
            "row": fl.pair.row,
        })
        quarantined += 1
        _finish(fl.pair.row, _quarantine_ledger_record(fl.pair, kind, err))

    def _schedule_retry(fl: _Flight, delay: float) -> None:
        nonlocal seq
        heapq.heappush(retries, (clock() + max(0.0, delay), seq, fl.pair.row))
        seq += 1

    def _fail(fl: _Flight, exc: BaseException) -> None:
        nonlocal retried
        if isinstance(exc, _BAD_INPUT) and not isinstance(
                exc, failpoints.InjectedFault):
            _quarantine(fl, "bad_input", exc)
            return
        # PoisonRequestError (the batcher's bisection isolated this pair
        # failing alone) is still retried: a transient device fault on a
        # singleton batch is indistinguishable from poison in one
        # sample, but real poison keeps failing and exhausts the
        # schedule — then it is quarantined as poison.
        fl.attempts += 1
        hint = getattr(exc, "retry_after_s", None)
        delay = fl.session.next_delay(hint_s=hint)
        if delay is None:
            kind = ("poison" if isinstance(exc, PoisonRequestError)
                    else "retries_exhausted")
            _quarantine(fl, kind, exc)
            return
        retried += 1
        obs.counter("bulk.retries").inc()
        obs.event("bulk_retry", row=fl.pair.row, attempt=fl.attempts,
                  delay_s=round(delay, 4),
                  error=f"{type(exc).__name__}: {exc}"[:200])
        _schedule_retry(fl, delay)

    def _launch(fl: _Flight) -> None:
        row = fl.pair.row
        shard = row // shard_size
        if shard not in shard_t0:
            shard_t0[shard] = clock()
        try:
            # Every dispatch (first launch and each retry) runs under
            # the flight's trace context: a dispatcher submit captures
            # it for its worker spans, and a client-backed submit
            # continues it across the wire.
            with trace.attach(fl.ctx):
                if fl.payload is None:
                    failpoints.fire("bulk.read", payload=fl.pair)
                    fl.bucket_key, fl.payload = prepare(fl.pair)
                failpoints.fire("bulk.dispatch", payload=fl.pair)
                fut = submit(fl.bucket_key, fl.payload)
        except RejectedError as exc:
            # Backpressure, not failure: the fleet refused admission
            # before attempting anything — requeue on the server's
            # hint without spending a retry attempt or budget token.
            _schedule_retry(fl, getattr(exc, "retry_after_s", poll_s))
            return
        except BaseException as exc:  # noqa: BLE001 — classified below
            _fail(fl, exc)
            return
        fut.add_done_callback(lambda f, r=row: doneq.put((r, f)))

    def _complete(row: int, fut) -> None:
        fl = inflight.get(row)
        if fl is None:  # late duplicate callback; already settled
            return
        exc = fut.exception()
        if exc is not None:
            _fail(fl, exc)
            return
        res = fut.result()
        res = getattr(res, "result", res)  # unwrap BatchResult
        if retry_policy.budget is not None:
            retry_policy.budget.record_success()
        _finish(row, record_fn(fl.pair, res))

    def _commit_ready() -> None:
        nonlocal since_checkpoint
        batch: List[dict] = []
        while ledger.next_row + len(batch) in ready:
            batch.append(ready.pop(ledger.next_row + len(batch)))
        if not batch:
            return
        first, last = batch[0]["row"], batch[-1]["row"]
        ledger.commit(batch)
        obs.counter("bulk.pairs_done").inc(len(batch))
        since_checkpoint += len(batch)
        crossed = range(first // shard_size,
                        (last + 1) // shard_size)
        for shard in crossed:  # shard boundary: force a durable cursor
            obs.counter("bulk.shards_done").inc()
            t0 = shard_t0.pop(shard, None)
            if t0 is not None:
                trace.emit_span("bulk.shard", max(0.0, clock() - t0),
                                shard=shard)
            ledger.write_checkpoint()
            since_checkpoint = 0
        if since_checkpoint >= checkpoint_every:
            ledger.write_checkpoint()
            since_checkpoint = 0

    try:
        while True:
            while len(inflight) + len(ready) < max_inflight and not exhausted:
                pair = next(source, None)
                if pair is None:
                    exhausted = True
                    break
                fl = _Flight(pair=pair, session=retry_policy.session(),
                             ctx=(trace.new_root(),), t0=clock())
                inflight[pair.row] = fl
                _launch(fl)
            now = clock()
            while retries and retries[0][0] <= now:
                _, _, row = heapq.heappop(retries)
                fl = inflight.get(row)
                if fl is not None:
                    _launch(fl)
            obs.gauge("bulk.inflight").set(len(inflight))
            if exhausted and not inflight and not ready:
                break
            if drive is not None:
                drive()
            else:
                wait = poll_s
                if retries:
                    wait = min(wait, max(0.0, retries[0][0] - clock()))
                try:
                    row, fut = doneq.get(timeout=max(wait, 1e-3))
                    _complete(row, fut)
                except queue.Empty:
                    pass
            while True:  # drain whatever else already completed
                try:
                    row, fut = doneq.get_nowait()
                except queue.Empty:
                    break
                _complete(row, fut)
            _commit_ready()
        ledger.write_checkpoint()
        duration = max(clock() - t_start, 1e-9)
        done_this_run = ledger.next_row - start_row
        summary = {
            "pairs_done": ledger.next_row,
            "pairs_this_run": done_this_run,
            "pairs_s": done_this_run / duration,
            "quarantined": quarantined,
            "retries": retried,
            "resumes": ledger.resumes,
            "start_row": start_row,
            "duration_s": duration,
            "truncated_tail": ledger.truncated_tail,
            "ledger": ledger.ledger_path,
            "quarantine": ledger.quarantine_path,
        }
        obs.event("bulk_done", **{k: v for k, v in summary.items()
                                  if isinstance(v, (int, float, bool))})
        return summary
    finally:
        ledger.close()
