"""Jax-free stand-in matcher for bulk crash/chaos drills.

The crash-resume e2e suite SIGKILLs subprocesses dozens of times; with
the real engine each leg would pay a jax import + compile. The echo
matcher keeps everything *around* the model real — `Replica` batchers,
circuit breakers, `FleetDispatcher` re-routing, `engine.device` /
`engine.rider` failpoints, shape buckets — and replaces only the model
step with a deterministic digest of the pair's file bytes. Determinism
matters: resumed runs must reproduce the interrupted run's results
bit-for-bit for the ledger byte-identity check to mean anything.
"""

from __future__ import annotations

import hashlib
import io
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..reliability import failpoints
from ..serving.fleet import MatchFleet, Replica
from .bulk import PairRow


@dataclass
class EchoPrepared:
    """Echo analogue of ``serving.engine.Prepared``: digest + meta."""

    bucket_key: Tuple
    digest: bytes  # sha256 over query||pano file bytes
    meta: dict = field(default_factory=dict)


def _image_dims(blob: bytes) -> Optional[Tuple[int, int]]:
    try:
        from PIL import Image

        with Image.open(io.BytesIO(blob)) as im:
            return im.size  # header-only decode
    except Exception:
        return None


def prepare(pair: PairRow) -> Tuple[Tuple, EchoPrepared]:
    """Read both images, digest them, bucket by query dimensions."""
    with open(pair.query, "rb") as fh:
        q = fh.read()
    with open(pair.pano, "rb") as fh:
        p = fh.read()
    dims = _image_dims(q)
    bucket_key = ("echo",) if dims is None else ("echo",) + dims
    digest = hashlib.sha256(q + b"\x00" + p).digest()
    prepared = EchoPrepared(bucket_key=bucket_key, digest=digest,
                            meta={"row": pair.row, **pair.extra})
    return bucket_key, prepared


class EchoPoisonError(RuntimeError):
    """A manifest-marked poison pair 'crashed the model'. Raised for
    the whole batch, exactly like a real device fault — the batcher's
    bisection must isolate the marked rider on its own."""


class EchoMatcher:
    """Batch runner with the engine's failpoint plants but no model.

    ``delay_s`` simulates model time per batch so chaos schedules
    (kill a replica while work is queued on it) have a real window.
    Pairs whose manifest row carries ``"poison"`` fail deterministically
    on every attempt — the injected-poison fixture for chaos gates.
    """

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = float(delay_s)
        self.batches = 0

    def run_batch(self, bucket_key, batch):
        failpoints.fire("engine.device", payload=bucket_key)
        for p in batch:
            failpoints.fire("engine.rider", payload=p)
        for p in batch:
            if p.meta.get("poison"):
                raise EchoPoisonError(
                    f"poison pair at manifest row {p.meta.get('row')}")
        if self.delay_s:
            time.sleep(self.delay_s)
        self.batches += 1
        out = []
        for p in batch:
            out.append({
                "matches": p.digest,  # the "answer": deterministic bytes
                "n_matches": 1 + p.digest[0] % 16,
                "timing": {"model_s": self.delay_s},
            })
        return out


def build_echo_fleet(n_replicas: int = 2, max_batch: int = 4,
                     max_queue: int = 64, max_delay_s: float = 0.005,
                     delay_s: float = 0.0) -> Tuple[MatchFleet, EchoMatcher]:
    """A real MatchFleet (batchers, breakers, dispatcher) over echo
    replicas — deadlines off, as every bulk caller runs it."""
    matcher = EchoMatcher(delay_s=delay_s)
    replicas = [
        Replica(
            f"echo{i}",
            runner=matcher.run_batch,
            max_batch=max_batch,
            max_queue=max_queue,
            max_delay_s=max_delay_s,
            default_timeout_s=None,
        )
        for i in range(n_replicas)
    ]
    return MatchFleet(replicas), matcher
