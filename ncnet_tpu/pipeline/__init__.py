"""Offline bulk pipelines: throughput workloads over the serving stack.

The paper's benchmarks (PF-Pascal, TSS, InLoc) are bulk jobs — a fixed
corpus of pairs mapped through the matcher — and at corpus scale the
binding constraint is surviving interruption without redoing work, not
step speed (FireCaffe, arXiv:1511.00175). This package runs that
workload on the same fleet the online service uses:

* :mod:`.bulk` — crash-safe resumable map of the matcher over a
  manifest of image pairs: exactly-once JSONL ledger + atomic cursor
  checkpoint, per-pair retries on a shared budget, poison quarantine,
  ``bulk.*`` failpoints/metrics (``tools/bulk_match.py`` is the CLI);
* :mod:`.echo` — a jax-free stand-in matcher so crash/chaos drills
  exercise the real replica/batcher/dispatcher stack in milliseconds.

Everything here is stdlib + obs + reliability + serving-core only; jax
enters only when the caller wires a real :class:`MatchEngine` fleet.
"""

from .bulk import (
    BulkLedger,
    LedgerError,
    PairRow,
    iter_manifest,
    manifest_digest,
    run_bulk,
)

__all__ = [
    "BulkLedger",
    "LedgerError",
    "PairRow",
    "iter_manifest",
    "manifest_digest",
    "run_bulk",
]
