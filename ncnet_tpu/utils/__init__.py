"""Shared utilities: file helpers, profiling/tracing, plotting, batching.

Covers the reference's L0 layer (lib/py_util.py, lib/plot.py, the
torch_util helpers) plus the observability subsystem SURVEY.md §5 calls
for (the reference has none — progress is bare prints).
"""

from .py_util import create_file_path
from .profiling import PhaseTimer, trace_context, phase
from .batching import collate_ragged, softmax_1d, expand_dim, str_to_bool

__all__ = [
    "create_file_path",
    "PhaseTimer",
    "trace_context",
    "phase",
    "collate_ragged",
    "softmax_1d",
    "expand_dim",
    "str_to_bool",
]
