"""Profiling & tracing (SURVEY.md §5: the reference has none — prints only).

Two layers:
  * `trace_context(logdir)` — wraps `jax.profiler.trace` so a whole
    phase can be captured for TensorBoard/Perfetto inspection.
  * `PhaseTimer` / `phase(...)` — lightweight wall-clock phase timing
    with device synchronization (block_until_ready on a probe value),
    for per-phase breakdowns in benches and evals without a trace.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional


@contextlib.contextmanager
def trace_context(logdir: Optional[str]):
    """jax.profiler.trace if logdir is set; no-op otherwise.

    The ``profile_capture`` run-log events bracketing the capture carry
    the wall-clock window tools/trace_export.py uses to align the
    profiler's device timeline with the run log's spans.
    """
    if not logdir:
        yield
        return
    import time as _time

    import jax

    from .. import obs

    obs.event("profile_capture", phase="start", logdir=logdir,
              t_capture_wall=_time.time())
    with jax.profiler.trace(logdir):
        yield
    obs.event("profile_capture", phase="end", logdir=logdir,
              t_capture_wall=_time.time())


class PhaseTimer:
    """Accumulates wall-clock time per named phase.

    Usage:
        timer = PhaseTimer()
        with timer.phase("forward", sync=lambda: corr):
            corr = step(...)
        print(timer.report())

    `sync=` takes a zero-arg callable evaluated when the phase CLOSES
    (so it can reference values produced inside the block); the timer
    blocks on the returned jax value before stopping the clock, so
    TPU-async dispatch is not misattributed to later phases. A plain
    jax array is also accepted for values that already exist at entry.
    """

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str, sync=None):
        start = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                try:
                    import jax

                    jax.block_until_ready(sync() if callable(sync) else sync)
                except Exception:
                    pass
            self.totals[name] += time.perf_counter() - start
            self.counts[name] += 1

    def report(self) -> str:
        lines = []
        for name in sorted(self.totals, key=lambda n: -self.totals[n]):
            t, c = self.totals[name], self.counts[name]
            lines.append(f"{name:30s} {t:9.3f}s  ({c} calls, {t / max(c, 1):8.4f}s avg)")
        return "\n".join(lines)

    def as_dict(self) -> dict:
        return {k: {"total_s": self.totals[k], "calls": self.counts[k]} for k in self.totals}


_GLOBAL_TIMER = PhaseTimer()


def phase(name: str, sync=None):
    """Module-level convenience: time a phase on the global timer."""
    return _GLOBAL_TIMER.phase(name, sync=sync)


def global_timer() -> PhaseTimer:
    return _GLOBAL_TIMER


def timed_steady(fn, *xs, iters: int = 3):
    """Time fn(*xs): returns (first_s, steady_s, out).

    first_s covers compile + first run; steady_s is the mean of `iters`
    further runs. Each run is closed by materializing a host-side probe of
    the outputs: on tunneled backends (axon) block_until_ready can return
    before execution completes, and only a host fetch reliably closes the
    iteration (the technique bench.py uses). The probe packs one element of
    EVERY leaf into a single scalar fetch — per-leaf fetches serialize one
    tunnel round trip each (~40 ms on axon), which inflated multi-output
    stages by up to 10 round trips per iteration before round 2's
    re-measurement. Shared by tools/profile_inloc.py and
    tools/bench_conv4d.py so their numbers stay comparable.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    def close(out):
        leaves = [l for l in jax.tree.leaves(out) if hasattr(l, "ravel")]
        if not leaves:
            return
        # Async dispatches chain on device; only the final float() blocks,
        # so the host pays one round trip per iteration, not one per leaf.
        probe = leaves[0].ravel()[0].astype(jnp.float32)
        for leaf in leaves[1:]:
            probe = probe + leaf.ravel()[0].astype(jnp.float32)
        float(probe)

    t0 = _time.perf_counter()
    out = fn(*xs)
    close(out)
    first = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    for _ in range(iters):
        close(fn(*xs))
    steady = (_time.perf_counter() - t0) / max(iters, 1)
    return first, steady, out


class AlarmTimeout(BaseException):
    """Raised by run_with_alarm when the wall-clock bound expires.

    Deliberately a BaseException: the bench tools fence individual
    candidates with broad `except Exception` handlers, and a phase-level
    timeout must fly past those to the session driver instead of being
    logged as one more failed candidate (which would consume the one-shot
    alarm and leave the rest of the phase unfenced).
    """


def run_with_alarm(seconds: int, fn, *args, **kwargs):
    """Run fn bounded by SIGALRM; raises AlarmTimeout on expiry.

    The per-experiment fence for hardware sessions: a single pathological
    compile otherwise hangs the whole one-dial experiment queue (observed
    2026-07-31: the pre-kernel XLA extraction formulation sat >20 min in
    the tunnel's remote-compile helper and starved every later phase).
    SIGALRM interrupts the blocking HTTP wait in the main thread; the jax
    client survives to run the next experiment. Main-thread only — call
    sites are the sequential tool drivers (tools/tpu_session.py,
    tools/bench_extract.py).

    Nesting-safe both ways: an inner fence arms min(its bound, the outer
    fence's remaining time) — it can never extend the outer deadline —
    and re-arms the outer's remaining time (minus the elapsed inner run,
    floor 1 s) on exit, so a per-candidate fence can neither cancel nor
    suspend the session's phase fence. Once the outer budget is spent,
    every subsequent inner call is clamped to ~1 s, so a phase whose
    per-candidate handlers swallow AlarmTimeout still drains in seconds
    per remaining candidate instead of minutes.
    """
    import signal
    import time as _time

    start = _time.monotonic()
    # Bound BEFORE installing the handler: an outer alarm firing in the
    # window between signal.signal() and the clamped assignment below
    # must raise AlarmTimeout, not NameError (ADVICE r3). Overwritten
    # with the clamped value before signal.alarm() arms anything.
    armed = int(seconds)

    def _handler(signum, frame):
        # Report the ACTUALLY-ARMED duration: an inner fence clamped to an
        # outer fence's remaining time (or the 1 s floor) would otherwise
        # claim its caller's full bound and mislead session-log analysis
        # of which fence fired (ADVICE r2).
        raise AlarmTimeout(
            f"timed out after {armed}s"
            + (f" (requested {seconds}s)" if armed != int(seconds) else "")
        )

    # Handler install happens INSIDE the try: if an outer alarm fires in
    # the window right after signal.signal(), the raise must still run
    # the finally (restoring the outer handler) or the session-level
    # fence would be silently dead afterwards.
    old_handler = None
    prev_remaining = None
    try:
        old_handler = signal.signal(signal.SIGALRM, _handler)
        prev_remaining = signal.alarm(0)  # read + cancel any outer fence
        arm = int(seconds)
        if prev_remaining:
            arm = min(arm, prev_remaining)
        armed = max(1, arm)
        signal.alarm(armed)
        return fn(*args, **kwargs)
    finally:
        # old_handler None means signal.signal itself raised (e.g. from
        # a non-main thread) — nothing was installed or disarmed, so
        # touching the alarm here would cancel an OUTER fence that was
        # never read and can never be re-armed.
        if old_handler is not None:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old_handler)
            if prev_remaining:
                elapsed = int(_time.monotonic() - start)
                signal.alarm(max(1, prev_remaining - elapsed))


def dial_devices(timeout: float):
    """jax.devices() under a watchdog thread.

    A wedged accelerator tunnel blocks jax.devices() indefinitely (observed
    on the axon TPU backend when a dead client's lease lingers); returns the
    device list, or None if the dial did not complete within `timeout`
    seconds. Shared by bench.py and tools/profile_inloc.py.
    """
    import threading

    import jax

    out = []
    th = threading.Thread(target=lambda: out.append(jax.devices()), daemon=True)
    th.start()
    th.join(timeout)
    return out[0] if out else None


def machine_tag() -> str:
    """Short fingerprint of the host CPU feature set.

    XLA:CPU AOT cache entries embed the compile machine's features; loading
    them on a different machine warns and risks SIGILL. /tmp persists across
    heterogeneous hosts in some setups, so the cache path must be
    machine-specific.
    """
    import hashlib
    import platform

    tag = platform.machine()
    try:
        picked = {}
        with open("/proc/cpuinfo") as f:
            for line in f:
                # Hash the model name too: two hosts can report identical
                # kernel flag lines while LLVM's direct cpuid detection
                # differs (observed 2026-07-31: stale AOT entries carrying
                # +amx-fp16 loaded on an amx-fp16-less host with SIGILL
                # warnings — the flags-only hash collided).
                for key in ("flags", "Features", "model name"):
                    if line.startswith(key) and key not in picked:
                        picked[key] = line
            if picked:
                tag += hashlib.sha1(
                    "".join(sorted(picked.values())).encode()
                ).hexdigest()[:8]
    except OSError:
        pass
    return tag


def chain_reps(fn, reps: int):
    """Wrap fn(*xs) so `reps` applications run inside ONE jit via lax.scan.

    Per-call timing through a tunneled backend has an ~85 ms host-RTT
    floor that swamps sub-100 ms kernels; chaining reps inside one
    executable amortizes it. Two measurement-critical properties, shared
    here so every bench tool keeps them in sync:
      * the carry multiplies into the first argument ((1 + carry*0),
        cast to its dtype so it cannot promote the workload) — a data
        dependence XLA cannot hoist or CSE away;
      * the carry consumes EVERY ELEMENT of EVERY output leaf (full
        sums), so no candidate's partial computation is dead-code-
        eliminated while an opaque competitor (pallas_call) still pays
        it. A single-element probe is not enough: XLA can slice
        backward through elementwise tails (e.g. the per-match delta
        decode) and compute just the probed element, under-reporting
        the candidate. The sums themselves are noise next to any stage
        worth timing.

    Time the result with timed_steady and divide by `reps`.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def reps_fn(*xs):
        def body(carry, _):
            first = xs[0] * (1.0 + carry * 0.0).astype(xs[0].dtype)
            out = fn(first, *xs[1:])
            leaves = [l for l in jax.tree.leaves(out) if hasattr(l, "ravel")]
            probe = jnp.float32(0)
            for leaf in leaves:
                probe = probe + jnp.sum(leaf.astype(jnp.float32))
            return probe, ()

        out, _ = lax.scan(body, jnp.float32(0), None, length=reps)
        return out

    return jax.jit(reps_fn)


def setup_compile_cache(path: str = ""):
    """Enable the persistent XLA compilation cache (minutes-long InLoc-shape
    compiles amortize across processes)."""
    import os

    import jax

    # Repo-local by default (NOT /tmp): the round-4 container restart wiped
    # /tmp and cost every warm compile of the round — cold InLoc-shape
    # compiles through the remote-compile helper are the single biggest
    # tunnel-window tax (20-40 s each, pathological >20 min). The repo dir
    # survives restarts; machine_tag keeps caches from different backends
    # apart.
    _repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    jax.config.update(
        "jax_compilation_cache_dir",
        path
        or os.environ.get(
            "NCNET_TPU_COMPILE_CACHE",
            os.path.join(_repo, ".jax_cache", machine_tag()),
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def run_bench_matrix(runs, *, dial_timeout=300.0, fence=1500.0,
                     knobs=(), log=print, on_result=None):
    """Shared driver for headline A/B matrices over trace-time env knobs.

    One dial, then bench.py's main() in-process per (label, env) run,
    each under a SIGALRM fence plus the hard-exit watchdog (a remote-
    compile wait stuck in native code defers signal delivery forever —
    the documented wedge class). Every knob in `knobs` is stripped
    before each run so combos never leak between lines. Used by
    tools/bench_strategies_ab.py and tools/bench_knob_ab.py; the fuller
    tools/tpu_session.py keeps its own loop (it additionally snapshots
    and restores operator-inherited overrides around the matrix).

    `on_result(label, headline_or_None)` — when given, each run's
    stdout is captured (bench's contract: ONE JSON line) and the parsed
    headline dict is handed to the callback (None on timeout/failure/
    unparseable output), so a caller can emit its OWN one-line summary
    without bench lines interleaving on stdout. Without the callback,
    bench lines go to stdout exactly as before.

    Returns 0, or 2 when the dial timed out.
    """
    import contextlib
    import importlib.util
    import io
    import json
    import os
    import traceback

    from ..obs import Watchdog

    setup_compile_cache()
    log(f"dialing (watchdog {dial_timeout:.0f}s)...")
    if dial_devices(dial_timeout) is None:
        log("dial timed out; aborting")
        return 2

    # Hard ceiling past the SIGALRM fence: a remote-compile wait stuck in
    # native code defers signal delivery forever (the documented wedge
    # class), so a daemon-thread deadline is the only way out.
    watchdog = Watchdog(label="bench_matrix", log=log).start()

    os.environ["NCNET_BENCH_DIAL_TIMEOUT"] = "120"
    os.environ["NCNET_BENCH_NO_REEXEC"] = "1"

    def _load_bench():
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "..",
            "bench.py",
        )
        spec = importlib.util.spec_from_file_location("bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    for label, env in runs:
        for k in knobs:
            os.environ.pop(k, None)
        os.environ.update(env)
        log(f"=== bench[{label}] env={env} ===")
        watchdog.arm(fence + 180)
        parsed = None
        try:
            if on_result is None:
                run_with_alarm(int(fence), _load_bench().main)
            else:
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    run_with_alarm(int(fence), _load_bench().main)
                for line in buf.getvalue().splitlines():
                    if line.strip().startswith("{"):
                        try:
                            parsed = json.loads(line)
                        except ValueError:
                            pass
        except AlarmTimeout as exc:
            log(f"bench[{label}] TIMED OUT: {exc}")
        except Exception:  # noqa: BLE001
            log(f"bench[{label}] FAILED:\n{traceback.format_exc()}")
        finally:
            watchdog.disarm()
            for k in env:
                os.environ.pop(k, None)
        if on_result is not None:
            on_result(label, parsed)
    log("A/B DONE")
    return 0
