"""Aggregate a JAX/XLA device trace into per-op / per-stage cost tables.

Shared machinery behind ``tools/trace_optable.py`` (the human-readable
table: see that tool's docstring for how it resolved the round-2/3 stage
attribution) and ``bench.py``'s utilization block (VERDICT r3 weak #5:
the headline JSON should carry achieved TFLOP/s / HBM GB/s / %-of-peak
so MFU regressions are visible in ``BENCH_r*.json`` without a manual
trace read).

Reads the ``*.trace.json.gz`` files ``jax.profiler.trace`` drops under
``<dir>/plugins/profile/<stamp>/``. Only device (TPU) planes attach the
``long_name``/``model_flops``/``bytes_accessed`` metadata this module
aggregates — a CPU-smoke trace has none, and ``aggregate`` returns None
for it rather than fabricating numbers.

Caveat on ``bytes_accessed``: it is XLA's cost-model LOGICAL traffic
(every operand read + output write), not measured DRAM transactions — an
op whose operands stay resident in VMEM/caches can show >100% of HBM
peak. Useful as a roofline locator per stage; not a DRAM counter.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from typing import Optional

# v5e per-chip peaks (the only TPU generation this framework has run on;
# the bench JSON records the assumed peaks next to the derived fractions
# so a different chip's numbers are reinterpretable).
PEAK_TFLOPS_BF16 = 197.0
PEAK_HBM_GBS = 819.0

# Source-file -> pipeline-stage rollup for the per-stage utilization
# table. Substring matches against the `source` metadata XLA attaches
# (paths relative to the ncnet_tpu package).
STAGE_OF_SOURCE = (
    ("models/backbone", "backbone"),
    ("ops/correlation", "corr_pool"),
    ("ops/pallas_kernels", "corr_pool"),
    ("ops/pool4d", "corr_pool"),
    ("ops/conv4d", "consensus"),
    ("ops/matches", "extract"),
    ("ops/extract_kernel", "extract"),
    ("ops/mutual", "extract"),
)


def load_events(trace_dir: str):
    """Newest capture's (path, traceEvents) under `trace_dir`."""
    pats = sorted(
        glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz"))
    )
    if not pats:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {trace_dir}/plugins/profile/"
        )
    path = max(pats, key=os.path.getmtime)
    with gzip.open(path) as f:
        data = json.load(f)
    return path, data["traceEvents"]


def device_pid(events) -> Optional[int]:
    """pid of the accelerator plane, or None (e.g. CPU-smoke traces)."""
    for e in events:
        if (
            e.get("ph") == "M"
            and e.get("name") == "process_name"
            and "TPU" in e.get("args", {}).get("name", "")
        ):
            return e["pid"]
    return None


def op_tids(events, pid) -> Optional[set]:
    """tids of the device plane's per-op line(s), or None to accept all.

    A capture's device plane carries several lines (tids): "XLA Ops"
    (one X event per op execution) plus umbrella lines — "XLA Modules",
    step markers, name-scope rollups. Summing across ALL lines double
    counts: an umbrella event spans the very ops it contains, and newer
    trace converters attach the same ``long_name``/cost args to it.
    That is the 2026-08-01 session_1128 artifact (docs/NEXT.md): the
    attributed device total came out ~1.9x the traced wall, and the
    umbrella's sourceless share masqueraded as a dominant "other" stage
    equal to the whole wall.

    Prefer the line(s) named exactly "XLA Ops" — a substring match also
    catches "Async XLA Ops", an empty-or-DMA line whose presence made
    the round-5 capture report op_lines=2 for a single-core trace. When
    the converter names differ, fall back to dropping umbrella-shaped
    lines by event count — an umbrella line has one event per module
    execution, an op line has orders of magnitude more, and a genuine
    concurrent per-core op line has the same order as its siblings, so
    keeping every tid within 10x of the busiest excludes umbrellas
    without halving a multi-core capture. None (accept all) when
    nothing distinguishes.
    """
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name" \
                and e.get("pid") == pid and "tid" in e:
            names[e["tid"]] = e.get("args", {}).get("name", "")
    ops_named = {t for t, n in names.items() if n == "XLA Ops"}
    if not ops_named:
        ops_named = {t for t, n in names.items() if "XLA Ops" in n}
    if ops_named:
        return ops_named
    counts = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("pid") == pid and "tid" in e \
                and "long_name" in (e.get("args") or {}):
            counts[e["tid"]] += 1
    if len(counts) > 1:
        top = counts.most_common(1)[0][1]
        return {t for t, c in counts.items() if c * 10 >= top}
    return None


def stage_of(src: str) -> str:
    for sub, stage in STAGE_OF_SOURCE:
        if sub in src:
            return stage
    return "other"


def aggregate(trace_dir: str, steps: int = 1) -> Optional[dict]:
    """Aggregate the newest capture into totals / per-category /
    per-source / per-op tables (durations divided by `steps`).

    Returns None when the trace has no accelerator plane or no op-level
    metadata (CPU smoke) — callers must not interpret that as zero cost.
    """
    path, ev = load_events(trace_dir)
    pid = device_pid(ev)
    if pid is None:
        return None
    tids = op_tids(ev, pid)

    # The op line NESTS events flame-graph style: a control-flow
    # container (`while`, `conditional`) is emitted as one X event whose
    # span covers the per-iteration body ops, ALSO emitted on the same
    # tid. The bb5 scan block's `while.5` (source bench.py, i.e. "other")
    # carries device_duration/model_flops for its whole body — summing
    # events flat double-counts every looped op (round-5 capture:
    # Σdur 1.89 s over a 0.96 s line span) and books the body's share a
    # second time under the container's sourceless "other" stage. The
    # honest rule is SELF time/flops/bytes: each event minus what its
    # same-line children already account for (clamped at 0 — a `while`
    # condition adds real overhead beyond its children; a container
    # whose metadata undercounts its body must not go negative).
    per_tid = collections.defaultdict(list)
    for e in ev:
        if e.get("ph") != "X" or e.get("pid") != pid:
            continue
        if tids is not None and e.get("tid") not in tids:
            continue  # umbrella lines (modules/steps/name scopes)
        a = e.get("args") or {}
        if "long_name" not in a:  # umbrella program / host rows
            continue
        per_tid[e["tid"]].append(e)

    by_cat = collections.Counter()
    by_src = {}
    ops = {}
    tot_us = 0.0
    tot_flops = 0.0
    tot_bytes = 0.0

    def emit(e, d, flops, nbytes):
        nonlocal tot_us, tot_flops, tot_bytes
        a = e.get("args") or {}
        src = a.get("source", "<none>").split("/ncnet_tpu/")[-1]
        by_cat[a.get("hlo_category", "?")] += d
        s = by_src.setdefault(src, dict(us=0.0, flops=0.0, bytes=0.0))
        s["us"] += d
        tot_us += d
        # FLOPs/bytes are per-op-program constants replicated across the
        # op's executions; every X event is one execution, so summing
        # per event then dividing by `steps` gives per-step totals.
        s["flops"] += flops
        s["bytes"] += nbytes
        tot_flops += flops
        tot_bytes += nbytes
        op = ops.setdefault(
            e["name"],
            dict(us=0.0, flops=0.0, bytes=0.0,
                 cat=a.get("hlo_category"), src=src),
        )
        op["us"] += d
        op["flops"] += flops
        op["bytes"] += nbytes

    for evs in per_tid.values():
        evs.sort(key=lambda e: (e["ts"], -float(e.get("dur", 0))))
        stack = []  # [end_ts, event, self_us, self_flops, self_bytes]
        for e in evs:
            a = e.get("args") or {}
            ts = float(e["ts"])
            d = float(e["dur"])
            flops = float(a.get("model_flops", 0) or 0)
            nbytes = float(a.get("bytes_accessed", 0) or 0)
            while stack and stack[-1][0] <= ts:
                fin = stack.pop()
                emit(fin[1], max(fin[2], 0.0), max(fin[3], 0.0),
                     max(fin[4], 0.0))
            if stack:  # nested: charge only self share to the parent
                stack[-1][2] -= d
                stack[-1][3] -= flops
                stack[-1][4] -= nbytes
            stack.append([ts + d, e, d, flops, nbytes])
        while stack:
            fin = stack.pop()
            emit(fin[1], max(fin[2], 0.0), max(fin[3], 0.0),
                 max(fin[4], 0.0))

    if tot_us == 0.0:
        return None
    n = max(steps, 1)
    sec = tot_us / n * 1e-6
    return dict(
        path=path,
        steps=n,
        op_lines=len(tids) if tids is not None else None,
        total_ms=tot_us / n / 1e3,
        total_gflops=tot_flops / n / 1e9,
        total_gb=tot_bytes / n / 1e9,
        tflops=tot_flops / n / sec / 1e12,
        gbs=tot_bytes / n / sec / 1e9,
        mfu=tot_flops / n / sec / 1e12 / PEAK_TFLOPS_BF16,
        hbm_frac=tot_bytes / n / sec / 1e9 / PEAK_HBM_GBS,
        by_cat={k: v / n / 1e3 for k, v in by_cat.items()},
        by_src=by_src,
        ops=ops,
    )


def stage_rollup(agg: dict) -> dict:
    """Per-stage {ms, tflops, gbs, mfu, hbm_frac} from aggregate()'s
    by_src table (stage mapping: STAGE_OF_SOURCE)."""
    n = agg["steps"]
    stages = {}
    for src, v in agg["by_src"].items():
        s = stages.setdefault(
            stage_of(src), dict(us=0.0, flops=0.0, bytes=0.0)
        )
        s["us"] += v["us"]
        s["flops"] += v["flops"]
        s["bytes"] += v["bytes"]
    out = {}
    for name, s in sorted(stages.items(), key=lambda kv: -kv[1]["us"]):
        sec = s["us"] / n * 1e-6
        if sec <= 0:
            continue
        tf = s["flops"] / n / sec / 1e12
        gbs = s["bytes"] / n / sec / 1e9
        out[name] = dict(
            ms=round(s["us"] / n / 1e3, 2),
            tflops=round(tf, 2),
            gbs=round(gbs, 1),
            mfu=round(tf / PEAK_TFLOPS_BF16, 4),
            hbm_frac=round(gbs / PEAK_HBM_GBS, 4),
        )
    return out
