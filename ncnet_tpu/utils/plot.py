"""Plotting helpers (parity: lib/plot.py:6-29 + show_matches2_horizontal.m).

Headless-safe: forces the Agg backend on import of the plotting calls.
"""

from __future__ import annotations

import numpy as np

from ..data.normalization import IMAGENET_MEAN, IMAGENET_STD


def _headless_matplotlib():
    """Select Agg for headless saving — but never retroactively: if pyplot
    is already imported (e.g. a notebook's inline backend) leave it alone."""
    import sys

    if "matplotlib.pyplot" not in sys.modules:
        import matplotlib

        matplotlib.use("Agg")


def denormalize_for_display(image: np.ndarray) -> np.ndarray:
    """Invert ImageNet normalization to [0, 1] HWC for imshow
    (parity: lib/plot.py:6-17)."""
    img = np.asarray(image)
    if img.ndim == 4:
        img = img[0]
    if img.shape[0] in (1, 3):  # CHW -> HWC
        img = np.transpose(img, (1, 2, 0))
    mean = np.asarray(IMAGENET_MEAN).reshape(1, 1, -1)
    std = np.asarray(IMAGENET_STD).reshape(1, 1, -1)
    return np.clip(img * std + mean, 0.0, 1.0)


def save_image(image: np.ndarray, path: str, denormalize: bool = True) -> None:
    """Borderless image save (parity: lib/plot.py:20-29)."""
    _headless_matplotlib()
    import matplotlib.pyplot as plt

    img = denormalize_for_display(image) if denormalize else np.asarray(image)
    fig = plt.figure(frameon=False)
    fig.set_size_inches(img.shape[1] / 100.0, img.shape[0] / 100.0)
    ax = plt.Axes(fig, [0.0, 0.0, 1.0, 1.0])
    ax.set_axis_off()
    fig.add_axes(ax)
    ax.imshow(img, aspect="auto")
    fig.savefig(path, dpi=100)
    plt.close(fig)


def plot_matches_horizontal(
    image_a: np.ndarray,
    image_b: np.ndarray,
    points_a: np.ndarray,
    points_b: np.ndarray,
    path: str | None,
    inliers: np.ndarray | None = None,
    denormalize: bool = False,
    scores: np.ndarray | None = None,
):
    """Side-by-side pair with match lines (parity:
    lib_matlab/show_matches2_horizontal.m). points_*: [n, 2] pixels.

    Line coloring: with `scores` ([n] floats), each line is colored by
    its match score through the viridis colormap, min-max normalized
    over the drawn set (the Matlab driver's plots likewise encode score
    as line color); with `inliers` (and no scores), green/red; neither,
    all green. Saves to `path`; with path=None returns the figure
    (notebook use)."""
    if path is not None:
        _headless_matplotlib()
    import matplotlib.pyplot as plt

    a = denormalize_for_display(image_a) if denormalize else np.asarray(image_a)
    b = denormalize_for_display(image_b) if denormalize else np.asarray(image_b)
    h = max(a.shape[0], b.shape[0])

    def pad_to(img, h):
        if img.shape[0] == h:
            return img
        pad = np.zeros((h - img.shape[0],) + img.shape[1:], img.dtype)
        return np.concatenate([img, pad], axis=0)

    canvas = np.concatenate([pad_to(a, h), pad_to(b, h)], axis=1)
    off = a.shape[1]

    fig, ax = plt.subplots(figsize=(canvas.shape[1] / 100.0, canvas.shape[0] / 100.0))
    ax.imshow(canvas)
    ax.set_axis_off()
    pa = np.asarray(points_a, dtype=np.float64)
    pb = np.asarray(points_b, dtype=np.float64)
    if scores is not None and np.asarray(scores).size == 0:
        scores = None  # zero matches: fall through to the inliers path
    if scores is not None:
        s = np.asarray(scores, dtype=np.float64)
        lo, hi = float(s.min()), float(s.max())
        rel = (s - lo) / (hi - lo) if hi > lo else np.ones_like(s)
        cmap = plt.get_cmap("viridis")
        colors = [cmap(r) for r in rel]
    else:
        inl = (np.ones(pa.shape[0], dtype=bool) if inliers is None
               else np.asarray(inliers, dtype=bool))
        colors = ["g" if i else "r" for i in inl]
    for i in range(pa.shape[0]):
        ax.plot([pa[i, 0], pb[i, 0] + off], [pa[i, 1], pb[i, 1]],
                color=colors[i], linewidth=0.5)
    ax.scatter(pa[:, 0], pa[:, 1], s=6, c="y")
    ax.scatter(pb[:, 0] + off, pb[:, 1], s=6, c="y")
    fig.tight_layout(pad=0)
    if path is None:
        return fig
    fig.savefig(path, dpi=100)
    plt.close(fig)
