"""Small filesystem helpers (parity: lib/py_util.py:4-10)."""

from __future__ import annotations

import os


def create_file_path(filename: str) -> None:
    """mkdir -p for the directory containing `filename`."""
    d = os.path.dirname(filename)
    if d:
        os.makedirs(d, exist_ok=True)
