"""Batching & misc helpers (parity: lib/torch_util.py:9-75).

The reference's `BatchTensorToVars` (dict -> GPU Variables) has no
TPU-side counterpart — device placement happens via jit/sharding — so
only the genuinely reusable pieces carry over.

This module also owns :class:`ShapeBuckets`, the same-shape bucket
accumulator shared by the batched eval drivers
(cli/eval_inloc._run_panos_batched) and the online serving micro-batcher
(serving/batcher.DeadlineBatcher) — ONE implementation of the grouping
heuristics so offline eval and online serving cannot drift.
"""

from __future__ import annotations

import numpy as np


class ShapeBuckets:
    """Same-shape bucket accumulator (promoted from cli/eval_inloc's
    `_MissGroups`, ISSUE 2 satellite 1).

    Encodes the grouping heuristics ONCE so every batched driver —
    cached and uncached `--pano_batch` eval, and the serving
    micro-batcher — shares them: a bucket dispatches the moment `p`
    same-shape items have accumulated; ragged groups are padded by
    repeating their last item (via :meth:`pad`; the padded iterations'
    outputs are discarded by the caller — unless the caller dispatches
    ragged, where the jitted program retraces per size); and the
    backlog across buckets is capped (default ``2p``) by early-flushing
    the fullest partial bucket rather than holding an unbounded number
    of decoded items (ADVICE r2).

    ``dispatch`` receives a chunk of 1..p items. :meth:`flush_ready` is
    the serving extension point: flush every bucket a predicate selects
    (deadline-near, linger-expired) without touching the accumulation
    heuristics above.
    """

    def __init__(self, p, dispatch, backlog_cap=None):
        self.p = p
        self.dispatch = dispatch  # receives a chunk of 1..p items
        self.backlog_cap = 2 * p if backlog_cap is None else backlog_cap
        self.groups = {}  # shape key -> list of items not yet dispatched

    def pad(self, chunk):
        return chunk + [chunk[-1]] * (self.p - len(chunk))

    def __len__(self):
        return sum(len(g) for g in self.groups.values())

    def add(self, shape_key, item):
        g = self.groups.setdefault(shape_key, [])
        g.append(item)
        if len(g) == self.p:
            self.dispatch(g[:])
            g.clear()
        elif len(self) > self.backlog_cap:
            big = max(self.groups.values(), key=len)
            self.dispatch(big[:])
            big.clear()

    def flush_ready(self, should_flush):
        """Dispatch every non-empty bucket ``should_flush(key, items)``
        selects (serving: deadline-near / linger-expired buckets)."""
        for key, g in self.groups.items():
            if g and should_flush(key, g):
                self.dispatch(g[:])
                g.clear()

    def drain(self):
        for g in self.groups.values():
            if g:
                self.dispatch(g[:])
                g.clear()


def collate_ragged(samples: list) -> dict:
    """Collate dict samples whose values may be ragged (parity:
    `collate_custom`, lib/torch_util.py:9-29): stackable arrays are
    stacked; everything else is kept as a list."""
    if not samples:
        return {}
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        first = vals[0]
        if isinstance(first, np.ndarray) and all(
            isinstance(v, np.ndarray) and v.shape == first.shape for v in vals
        ):
            out[key] = np.stack(vals)
        elif isinstance(first, (int, float, np.integer, np.floating)):
            out[key] = np.asarray(vals)
        else:
            out[key] = vals
    return out


def softmax_1d(x, axis: int = -1):
    """Numerically-stable softmax (parity: `Softmax1D`, lib/torch_util.py).

    Thin alias over jax.nn.softmax — the project convention
    (ncnet_tpu/ops/matches.py) — kept for API parity with the reference.
    """
    import jax

    return jax.nn.softmax(jax.numpy.asarray(x), axis=axis)


def expand_dim(x, axis: int, reps: int):
    """Insert an axis and tile it `reps` times (parity: `expand_dim`,
    lib/torch_util.py:63-66)."""
    import jax.numpy as jnp

    x = jnp.expand_dims(jnp.asarray(x), axis)
    tiles = [1] * x.ndim
    tiles[axis] = reps
    return jnp.tile(x, tiles)


def str_to_bool(v) -> bool:
    """argparse-friendly bool (parity: `str_to_bool`, lib/torch_util.py)."""
    if isinstance(v, bool):
        return v
    if str(v).lower() in ("yes", "true", "t", "y", "1"):
        return True
    if str(v).lower() in ("no", "false", "f", "n", "0"):
        return False
    raise ValueError(f"boolean value expected, got {v!r}")
