"""Batching & misc helpers (parity: lib/torch_util.py:9-75).

The reference's `BatchTensorToVars` (dict -> GPU Variables) has no
TPU-side counterpart — device placement happens via jit/sharding — so
only the genuinely reusable pieces carry over.
"""

from __future__ import annotations

import numpy as np


def collate_ragged(samples: list) -> dict:
    """Collate dict samples whose values may be ragged (parity:
    `collate_custom`, lib/torch_util.py:9-29): stackable arrays are
    stacked; everything else is kept as a list."""
    if not samples:
        return {}
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        first = vals[0]
        if isinstance(first, np.ndarray) and all(
            isinstance(v, np.ndarray) and v.shape == first.shape for v in vals
        ):
            out[key] = np.stack(vals)
        elif isinstance(first, (int, float, np.integer, np.floating)):
            out[key] = np.asarray(vals)
        else:
            out[key] = vals
    return out


def softmax_1d(x, axis: int = -1):
    """Numerically-stable softmax (parity: `Softmax1D`, lib/torch_util.py).

    Thin alias over jax.nn.softmax — the project convention
    (ncnet_tpu/ops/matches.py) — kept for API parity with the reference.
    """
    import jax

    return jax.nn.softmax(jax.numpy.asarray(x), axis=axis)


def expand_dim(x, axis: int, reps: int):
    """Insert an axis and tile it `reps` times (parity: `expand_dim`,
    lib/torch_util.py:63-66)."""
    import jax.numpy as jnp

    x = jnp.expand_dims(jnp.asarray(x), axis)
    tiles = [1] * x.ndim
    tiles[axis] = reps
    return jnp.tile(x, tiles)


def str_to_bool(v) -> bool:
    """argparse-friendly bool (parity: `str_to_bool`, lib/torch_util.py)."""
    if isinstance(v, bool):
        return v
    if str(v).lower() in ("yes", "true", "t", "y", "1"):
        return True
    if str(v).lower() in ("no", "false", "f", "n", "0"):
        return False
    raise ValueError(f"boolean value expected, got {v!r}")
