"""Deadline-aware retries: exponential backoff, full jitter, budget.

One retry policy for every transient-failure path in the repo — the
serving client's 503 backoff, loader IO, checkpoint IO — instead of a
per-call-site ad-hoc loop, because the failure modes of ad-hoc loops
are all the same: unbounded cumulative sleeping past the caller's
deadline, synchronized lockstep retries from clients that share a
clock edge, and retry storms that amplify an outage (every retry is
extra load on the thing that is already failing).

* **Exponential backoff + full jitter**: attempt ``k`` sleeps
  ``uniform(0, min(max_delay, base * 2**k))`` — the decorrelated form
  that spreads a thundering herd (the AWS architecture-blog result).
* **Deadline-aware**: an overall ``deadline_s`` caps the *sum* of
  sleeps; a retry that cannot finish before the deadline is not
  attempted, and each sleep is clipped to the time remaining.
* **Retry budget**: an optional shared :class:`RetryBudget` bounds the
  retry *rate* across calls (a token bucket refilled by successes) so
  a full outage degrades to roughly one retry per successful call
  instead of multiplying offered load.

Clock/sleep/rng are injectable: tests drive retry schedules with a
fake clock and assert on the exact sleep sequence.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

from .. import obs


class RetryBudget:
    """Token bucket bounding cross-call retry rate.

    Starts full at ``capacity``. Each retry spends one token; each
    *success* deposits ``refill_per_success`` (default 0.1: sustained,
    one retry per ten successes). An empty bucket means "stop retrying,
    fail fast" — the anti-amplification valve during a full outage.
    """

    def __init__(self, capacity: float = 10.0,
                 refill_per_success: float = 0.1):
        self.capacity = float(capacity)
        self.refill_per_success = float(refill_per_success)
        self._tokens = float(capacity)
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def record_success(self) -> None:
        with self._lock:
            self._tokens = min(self.capacity,
                               self._tokens + self.refill_per_success)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens < 1.0:
                return False
            self._tokens -= 1.0
            return True


class RetryPolicy:
    """Reusable retry schedule; one instance may serve many calls.

    ``max_attempts`` counts *total* tries (1 = no retries). Use
    :meth:`call` for the wrap-a-callable form or :meth:`session` when
    the retry loop must stay inline (the HTTP client inspects status
    codes and Retry-After hints between tries).
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay_s: float = 0.05,
        max_delay_s: float = 5.0,
        deadline_s: Optional[float] = None,
        budget: Optional[RetryBudget] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.budget = budget
        self.clock = clock
        self.sleep = sleep
        self.rng = rng if rng is not None else random.Random()

    def session(self, deadline_s: Optional[float] = None) -> "RetrySession":
        """A per-call session holding the attempt counter + deadline."""
        d = self.deadline_s if deadline_s is None else float(deadline_s)
        return RetrySession(self, deadline=None if d is None
                            else self.clock() + d)

    def call(self, fn: Callable, retry_on: Tuple[Type[BaseException], ...]
             = (OSError,), site: str = ""):
        """Run ``fn()``, retrying on ``retry_on`` per the schedule.

        The terminal exception is re-raised unchanged (callers keep
        their existing error contracts); every retry is an obs event so
        a run log shows transient-failure churn even when the call
        ultimately succeeds.
        """
        session = self.session()
        while True:
            try:
                result = fn()
            except retry_on as exc:
                delay = session.next_delay()
                if delay is None:
                    raise
                obs.counter("retry.attempts").inc()
                obs.event("retry", site=site or getattr(fn, "__name__", ""),
                          attempt=session.attempt,
                          delay_s=round(delay, 6),
                          error=f"{type(exc).__name__}: {exc}")
                self.sleep(delay)
                continue
            if self.budget is not None:
                self.budget.record_success()
            return result


class RetrySession:
    """One call's retry state: attempts used, absolute deadline."""

    def __init__(self, policy: RetryPolicy, deadline: Optional[float]):
        self.policy = policy
        self.deadline = deadline
        self.attempt = 0  # completed (failed) attempts so far

    def remaining_s(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - self.policy.clock()

    def next_delay(self, hint_s: Optional[float] = None) -> Optional[float]:
        """Seconds to sleep before the next attempt, or None = give up.

        ``hint_s`` (a server's Retry-After) acts as the floor of the
        jitter window: the sleep is ``uniform(hint, max(hint, backoff))``
        — the hint is honored, but synchronized clients still spread
        out. Returns None when attempts, deadline, or budget are
        exhausted; the caller raises its own terminal error.
        """
        p = self.policy
        self.attempt += 1
        if self.attempt >= p.max_attempts:
            return None
        if p.budget is not None and not p.budget.try_spend():
            obs.counter("retry.budget_exhausted").inc()
            return None
        ceiling = min(p.max_delay_s, p.base_delay_s * (2 ** (self.attempt - 1)))
        lo = 0.0 if hint_s is None else max(0.0, float(hint_s))
        delay = p.rng.uniform(lo, max(lo, ceiling))
        remaining = self.remaining_s()
        if remaining is not None:
            if remaining <= 0.0 or delay >= remaining:
                obs.counter("retry.deadline_exhausted").inc()
                return None
            delay = min(delay, remaining)
        return delay
