"""Circuit breaker for the serving engine's device dispatch.

When the accelerator path is *down* (device lost, compile storm,
wedged tunnel), every admitted request pays the full failure latency
— queue wait, dispatch, exception — before its client learns anything,
and the queue stays full of work that cannot succeed. The breaker
converts that into the cheapest possible answer: after
``failure_threshold`` consecutive dispatch failures it OPENS, and the
server front-door turns requests away immediately with 503 +
``Retry-After`` (clients' jittered backoff — reliability/retry.py — is
the cooperative half). After ``reset_timeout_s`` it goes HALF_OPEN and
lets ``half_open_probes`` real requests through: one success closes it
(the device came back), one failure re-opens it for another timeout.

State changes are loud: an obs ``breaker`` event per transition, a
``breaker.state`` gauge (0 closed / 1 half-open / 2 open), and — on
open — a one-shot flight-recorder dump (obs/flight.py) capturing the
last N events leading into the outage, which is exactly the window a
post-mortem needs.

Clock-injected and lock-guarded; tests drive open/half-open/close with
a fake clock and no real failures.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .. import obs
from ..obs import flight

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class BreakerOpenError(RuntimeError):
    """Dispatch refused: the breaker is open. Carries the Retry-After
    hint the server forwards to clients."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"circuit breaker open; retry after {retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing."""

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 10.0,
        half_open_probes: int = 1,
        name: str = "engine",
        clock: Callable[[], float] = time.monotonic,
        labels=None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_probes = max(int(half_open_probes), 1)
        self.name = name
        # e.g. {"replica": "r0"}: per-replica breaker series in a fleet
        # (and two breakers sharing one process registry in tests).
        self.labels = dict(labels or {})
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probes_inflight = 0
        self.transitions = 0

    # -- state transitions (callers hold no lock) --------------------------

    def _transition(self, new: str, **fields) -> None:
        """Move to ``new`` state; caller holds self._lock."""
        prev, self._state = self._state, new
        self.transitions += 1
        obs.gauge(f"breaker.{self.name}.state",
                  labels=self.labels).set(_STATE_GAUGE[new])
        # obs calls under the lock are safe (metrics use their own
        # locks) but the flight dump does file IO — defer it.
        self._pending_dump = (new == OPEN)
        self._last_event = dict(
            state=new, prev=prev,
            consecutive_failures=self._consecutive_failures, **fields
        )

    def _emit_transition(self) -> None:
        ev = self.__dict__.pop("_last_event", None)
        if ev is None:
            return
        obs.event("breaker", breaker=self.name, **ev)
        if self.__dict__.pop("_pending_dump", False):
            obs.counter(f"breaker.{self.name}.opens",
                        labels=self.labels).inc()
            # Cooldown-deduped: a flapping breaker dumps once per
            # episode window, not once per flap.
            flight.dump(f"breaker-open-{self.name}")

    # -- the guarded-call protocol ----------------------------------------

    def retry_after_s(self) -> float:
        """Suggested Retry-After while open (time to next probe)."""
        with self._lock:
            if self._state != OPEN or self._opened_at is None:
                return 0.0
            return max(
                self._opened_at + self.reset_timeout_s - self.clock(), 0.01
            )

    def admit(self) -> Optional[float]:
        """Front-door check (no side effects on counts): None = admit,
        else a Retry-After hint to reject with. Requests arriving after
        the reset timeout are admitted so they can serve as half-open
        probes."""
        with self._lock:
            if self._state != OPEN:
                return None
            if (self._opened_at is not None
                    and self.clock() - self._opened_at
                    >= self.reset_timeout_s):
                return None
            return max(
                self._opened_at + self.reset_timeout_s - self.clock(), 0.01
            )

    def allow(self) -> None:
        """Gate one dispatch; raises :class:`BreakerOpenError` or
        registers the call as a half-open probe."""
        with self._lock:
            if self._state == CLOSED:
                return
            now = self.clock()
            if self._state == OPEN:
                if (self._opened_at is None
                        or now - self._opened_at < self.reset_timeout_s):
                    retry = max(
                        (self._opened_at or now) + self.reset_timeout_s - now,
                        0.01,
                    )
                    raise BreakerOpenError(retry)
                self._transition(HALF_OPEN, reason="reset_timeout")
                self._probes_inflight = 0
            # HALF_OPEN: admit a bounded number of concurrent probes.
            if self._probes_inflight >= self.half_open_probes:
                exc = BreakerOpenError(max(self.reset_timeout_s, 0.01))
            else:
                self._probes_inflight += 1
                exc = None
        self._emit_transition()
        if exc is not None:
            raise exc

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                self._transition(CLOSED, reason="probe_success")
                self._opened_at = None
        self._emit_transition()

    def record_failure(self, exc: Optional[BaseException] = None) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                self._opened_at = self.clock()
                self._transition(OPEN, reason="probe_failure",
                                 error=_exc_str(exc))
            elif (self._state == CLOSED
                  and self._consecutive_failures >= self.failure_threshold):
                self._opened_at = self.clock()
                self._transition(OPEN, reason="failure_threshold",
                                 error=_exc_str(exc))
        self._emit_transition()

    def call(self, fn: Callable, *args, **kwargs):
        """``allow`` + run + record — the wrap-a-runner form the server
        uses around ``MatchEngine.run_batch``."""
        self.allow()
        try:
            result = fn(*args, **kwargs)
        except BreakerOpenError:
            raise
        except Exception as exc:
            self.record_failure(exc)
            raise
        self.record_success()
        return result

    # -- introspection ----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        """State dict for /healthz and tests."""
        with self._lock:
            snap = {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "transitions": self.transitions,
            }
            if self._state == OPEN and self._opened_at is not None:
                snap["retry_after_s"] = round(max(
                    self._opened_at + self.reset_timeout_s - self.clock(),
                    0.01,
                ), 3)
            return snap

    def reset(self) -> None:
        """Force-close (tests / operator action)."""
        with self._lock:
            self._state = CLOSED
            self._consecutive_failures = 0
            self._opened_at = None
            self._probes_inflight = 0
        obs.gauge(f"breaker.{self.name}.state",
                  labels=self.labels).set(0.0)


def _exc_str(exc: Optional[BaseException]) -> Optional[str]:
    return None if exc is None else f"{type(exc).__name__}: {exc}"
