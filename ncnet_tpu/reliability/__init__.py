"""Reliability subsystem: fault injection, retries, circuit breaking.

Production posture (ROADMAP north star, the FireCaffe / large-scale
training lineage in PAPERS.md): component failure is the steady state,
so every failure domain gets (1) a named injection site to *create*
the failure on demand, (2) a recovery policy, and (3) a test. The
three legs:

* :mod:`.failpoints` — named chaos-injection sites
  (``NCNET_FAILPOINTS="engine.device=error:0.5"``), planted through
  data, serving, and checkpoint paths;
* :mod:`.retry` — the shared deadline-aware
  :class:`~ncnet_tpu.reliability.retry.RetryPolicy` (exponential
  backoff + full jitter + retry budget);
* :mod:`.breaker` — the :class:`~ncnet_tpu.reliability.breaker.CircuitBreaker`
  around the serving engine's device dispatch.

Poison-batch isolation (bisecting a failed shared batch so one bad
rider cannot fail its co-batched strangers) lives with the batcher it
protects — ``serving/batcher.py`` — and is documented with the rest of
the contract in docs/RELIABILITY.md.

Everything here is stdlib + obs only: the serving client (which must
stay numpy/jax-free) imports it, and so can any test environment.
"""

from .breaker import CircuitBreaker, BreakerOpenError
from .failpoints import (
    Failpoint,
    FailpointRegistry,
    InjectedFault,
    failpoint,
)
from .retry import RetryBudget, RetryPolicy

from . import breaker, failpoints, retry

__all__ = [
    "CircuitBreaker",
    "BreakerOpenError",
    "Failpoint",
    "FailpointRegistry",
    "InjectedFault",
    "failpoint",
    "RetryBudget",
    "RetryPolicy",
    "breaker",
    "failpoints",
    "retry",
]
