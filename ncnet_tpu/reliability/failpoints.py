"""Named fault-injection sites ("failpoints") for chaos testing.

A production service is only as reliable as its *tested* failure
paths: a device error handler that has never fired is a hypothesis,
not a recovery policy. This module gives every failure domain in the
repo a named injection site that is a no-op in normal operation (one
dict lookup on a module-level registry) and, when armed, injects one
of three fault modes:

* ``error`` — raise :class:`InjectedFault` at the site;
* ``delay`` — sleep a configured duration (timeout / stall paths);
* ``corrupt`` — mangle a value passing through the site (NaN-poison a
  numpy array, truncate bytes) via :func:`corrupt`;
* ``kill`` — ``SIGKILL`` the process at the site (crash-resume drills:
  the process dies with no chance to flush or clean up, exactly like
  an OOM kill or a preemption).

Arming is either programmatic (tests: :func:`failpoint` context
manager, :func:`set_failpoint`) or environmental::

    NCNET_FAILPOINTS="engine.device=error:0.5,loader.read=delay:200ms"

Spec grammar, comma-separated ``site=mode[:args]`` terms:

* ``site=error`` / ``site=error:0.5`` — raise with probability (default
  1.0);
* ``site=error:1.0x3`` — ``xN`` caps total fires (the site disarms
  after N injections — "fail twice then recover" in one spec);
* ``site=delay:200ms`` / ``site=delay:1.5s:0.25`` — sleep, optional
  probability;
* ``site=corrupt`` / ``site=corrupt:0.1`` — corrupt values at
  :func:`corrupt` call sites;
* ``site=kill`` / ``site=kill:+3`` — SIGKILL the process; ``+N`` skips
  the first N evaluations of the site, so ``bulk.commit=kill:+3`` dies
  on exactly the 4th commit (deterministic crash placement for
  resume tests). ``+N`` composes with every mode.

Probabilistic sites draw from a per-site ``random.Random`` seeded by
``(NCNET_FAILPOINTS_SEED, site)`` — runs are deterministic given the
seed, and one site's draw order never perturbs another's.

Planted sites (grep ``failpoints.fire`` for the live list):

``loader.read`` (data/image_io), ``batcher.run``
(serving/batcher worker), ``engine.device`` (serving/engine dispatch),
``server.handle`` (serving/server request handler), ``client.transport``
(serving/client), ``checkpoint.save`` / ``checkpoint.save.commit`` /
``checkpoint.load`` (training/checkpoint), ``train.step``
(cli/train step loop; ``corrupt`` NaN-poisons the divergence
sentinel's resolved loss copy — obs/train_watch), ``bulk.read`` /
``bulk.dispatch`` / ``bulk.commit`` / ``bulk.checkpoint``
(pipeline/bulk), ``membership.lease`` (parallel/membership lease
renewal; ``kill`` here SIGKILLs a host mid-heartbeat — the canonical
host-death drill), ``membership.detect`` (dead-host detection sweep),
``elastic.resume`` (training/elastic survivor resume entry). The full
site table with failure domains lives in docs/RELIABILITY.md and is
lint-enforced (tests/test_failpoint_docs_lint.py).

Every injection is an obs event (``failpoint``) and a counter
(``failpoint.<site>``) so a chaos run's run log records exactly what
was injected where (docs/RELIABILITY.md).
"""

from __future__ import annotations

import contextlib
import os
import random
import re
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from .. import obs


class InjectedFault(RuntimeError):
    """An error injected by an armed failpoint (never raised in
    production unless someone armed the site)."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at failpoint {site!r}")
        self.site = site


_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m)?$")


def _parse_duration_s(text: str) -> Optional[float]:
    m = _DURATION_RE.match(text)
    if not m:
        return None
    value = float(m.group(1))
    unit = m.group(2)
    if unit == "ms":
        return value / 1e3
    if unit == "m":
        return value * 60.0
    # "s" or a bare float: delay args always carry a unit in specs,
    # but programmatic strings may not.
    return value


@dataclass
class Failpoint:
    """One armed site: mode + probability + optional fire cap/matcher."""

    site: str
    mode: str  # "error" | "delay" | "corrupt" | "kill"
    prob: float = 1.0
    delay_s: float = 0.0
    max_fires: Optional[int] = None
    #: Skip the first N evaluations of the site before it can fire
    #: (``+N`` in specs) — pins a crash to "the Nth+1 commit".
    skip_first: int = 0
    #: Optional payload predicate: the site only fires for payloads the
    #: callable accepts (per-rider poison in a shared batch).
    match: Optional[Callable[[Any], bool]] = None
    #: Optional custom corruptor for ``corrupt`` mode.
    corruptor: Optional[Callable[[Any], Any]] = None
    fires: int = field(default=0)
    skips: int = field(default=0)

    def spent(self) -> bool:
        return self.max_fires is not None and self.fires >= self.max_fires


def _parse_term(term: str) -> Failpoint:
    site, _, spec = term.partition("=")
    site, spec = site.strip(), spec.strip()
    if not site or not spec:
        raise ValueError(f"bad failpoint term {term!r} (want site=mode[:args])")
    parts = spec.split(":")
    mode = parts[0].strip().lower()
    if mode not in ("error", "delay", "corrupt", "kill"):
        raise ValueError(f"bad failpoint mode {mode!r} in {term!r}")
    prob, delay_s, max_fires, skip_first = 1.0, 0.0, None, 0
    args = [a.strip() for a in parts[1:] if a.strip()]
    if mode == "delay":
        if not args:
            raise ValueError(f"delay failpoint {term!r} needs a duration")
        delay_s = _parse_duration_s(args.pop(0))
        if delay_s is None:
            raise ValueError(f"bad delay duration in {term!r}")
    for arg in args:
        if arg.startswith("+"):
            skip_first = int(arg[1:])
            continue
        body, _, cap = arg.partition("x")
        if cap:
            max_fires = int(cap)
        if body:
            prob = float(body)
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"failpoint probability out of [0,1] in {term!r}")
    return Failpoint(site=site, mode=mode, prob=prob, delay_s=delay_s,
                     max_fires=max_fires, skip_first=skip_first)


def parse_spec(spec: str) -> Dict[str, Failpoint]:
    """Parse an ``NCNET_FAILPOINTS`` spec string into site -> Failpoint."""
    out: Dict[str, Failpoint] = {}
    for term in spec.split(","):
        term = term.strip()
        if not term:
            continue
        fp = _parse_term(term)
        out[fp.site] = fp
    return out


class FailpointRegistry:
    """Process-global map of armed sites; `fire` is the hot-path check.

    The unarmed fast path is one lock-free dict ``get`` returning None
    — cheap enough to plant on per-request serving paths. All mutation
    happens under a lock; ``_sites`` is swapped wholesale so readers
    never see a half-built table.
    """

    def __init__(self, seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._lock = threading.Lock()
        self._sites: Dict[str, Failpoint] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._sleep = sleep
        self.seed = seed

    # -- arming -----------------------------------------------------------

    def _seed(self) -> int:
        if self.seed is not None:
            return self.seed
        try:
            return int(os.environ.get("NCNET_FAILPOINTS_SEED", "0"))
        except ValueError:
            return 0

    def configure(self, spec: str) -> Dict[str, Failpoint]:
        """Replace the armed set from a spec string ('' disarms all)."""
        sites = parse_spec(spec)
        with self._lock:
            self._sites = sites
            self._rngs = {}
        if sites:
            obs.event("failpoint", action="configure",
                      sites={s: fp.mode for s, fp in sites.items()})
        return sites

    def configure_from_env(self) -> Dict[str, Failpoint]:
        return self.configure(os.environ.get("NCNET_FAILPOINTS", ""))

    def set(self, site: str, mode: str, prob: float = 1.0,
            delay_s: float = 0.0, max_fires: Optional[int] = None,
            skip_first: int = 0,
            match: Optional[Callable[[Any], bool]] = None,
            corruptor: Optional[Callable[[Any], Any]] = None) -> Failpoint:
        """Arm (or re-arm) one site programmatically."""
        if mode not in ("error", "delay", "corrupt", "kill"):
            raise ValueError(f"bad failpoint mode {mode!r}")
        fp = Failpoint(site=site, mode=mode, prob=prob, delay_s=delay_s,
                       max_fires=max_fires, skip_first=skip_first,
                       match=match, corruptor=corruptor)
        with self._lock:
            sites = dict(self._sites)
            sites[site] = fp
            self._sites = sites
            self._rngs.pop(site, None)
        return fp

    def clear(self, site: Optional[str] = None) -> None:
        """Disarm one site, or all of them (site=None)."""
        with self._lock:
            if site is None:
                self._sites = {}
                self._rngs = {}
            else:
                sites = dict(self._sites)
                sites.pop(site, None)
                self._sites = sites
                self._rngs.pop(site, None)

    def active(self) -> Dict[str, Failpoint]:
        """Snapshot of the armed sites (for /healthz and reports)."""
        return dict(self._sites)

    # -- firing -----------------------------------------------------------

    def _should_fire(self, fp: Failpoint, payload: Any) -> bool:
        with self._lock:
            if fp.spent():
                return False
            if fp.skips < fp.skip_first:
                fp.skips += 1
                return False
            if fp.match is not None:
                try:
                    if not fp.match(payload):
                        return False
                except Exception:
                    return False
            if fp.prob < 1.0:
                rng = self._rngs.get(fp.site)
                if rng is None:
                    rng = random.Random(f"{self._seed()}:{fp.site}")
                    self._rngs[fp.site] = rng
                if rng.random() >= fp.prob:
                    return False
            fp.fires += 1
        obs.counter(f"failpoint.{fp.site}").inc()
        obs.event("failpoint", site=fp.site, mode=fp.mode, fire=fp.fires)
        return True

    def fire(self, site: str, payload: Any = None) -> None:
        """Evaluate one site: no-op when unarmed; may sleep or raise."""
        fp = self._sites.get(site)
        if fp is None or fp.mode == "corrupt":
            return
        if not self._should_fire(fp, payload):
            return
        if fp.mode == "delay":
            self._sleep(fp.delay_s)
        elif fp.mode == "kill":
            # A real crash, not an exception: no finally blocks, no
            # buffered-write flush — whatever isn't fsynced is gone.
            os.kill(os.getpid(), signal.SIGKILL)
        else:
            raise InjectedFault(site)

    def corrupt(self, site: str, value: Any) -> Any:
        """Pass ``value`` through the site; an armed corrupt-mode site
        returns a mangled copy (NaN-poisoned array, truncated bytes)."""
        fp = self._sites.get(site)
        if fp is None or fp.mode != "corrupt":
            return value
        if not self._should_fire(fp, value):
            return value
        if fp.corruptor is not None:
            return fp.corruptor(value)
        return _default_corrupt(value)


def _default_corrupt(value: Any) -> Any:
    try:
        import numpy as np

        if isinstance(value, np.ndarray) and value.size:
            out = np.array(value)
            if np.issubdtype(out.dtype, np.floating):
                out.reshape(-1)[:: max(out.size // 16, 1)] = np.nan
            else:
                out.reshape(-1)[:: max(out.size // 16, 1)] = 0
            return out
    except ImportError:
        pass
    if isinstance(value, (bytes, bytearray)) and value:
        return value[: max(len(value) // 2, 1)]
    return value


_REGISTRY = FailpointRegistry()
# Env arming at import: ANY entry point (serving, eval, train, a bare
# pytest process) honors NCNET_FAILPOINTS without per-CLI wiring.
_REGISTRY.configure_from_env()


def registry() -> FailpointRegistry:
    return _REGISTRY


def fire(site: str, payload: Any = None) -> None:
    """Module-level site check (the form planted in library code)."""
    _REGISTRY.fire(site, payload=payload)


def corrupt(site: str, value: Any) -> Any:
    return _REGISTRY.corrupt(site, value)


def configure(spec: str) -> Dict[str, Failpoint]:
    return _REGISTRY.configure(spec)


def configure_from_env() -> Dict[str, Failpoint]:
    return _REGISTRY.configure_from_env()


def set_failpoint(site: str, mode: str, **kwargs) -> Failpoint:
    return _REGISTRY.set(site, mode, **kwargs)


def clear(site: Optional[str] = None) -> None:
    _REGISTRY.clear(site)


def active() -> Dict[str, Failpoint]:
    return _REGISTRY.active()


@contextlib.contextmanager
def failpoint(site: str, mode: str, **kwargs):
    """Arm one site for a block (the test-suite form); always disarms."""
    fp = _REGISTRY.set(site, mode, **kwargs)
    try:
        yield fp
    finally:
        _REGISTRY.clear(site)
