"""Program cost cards + device HBM accounting (the cost observatory).

A *cost card* is the compile-time answer to "what does this program
cost": the XLA ``cost_analysis()`` FLOP/byte totals and the
``memory_analysis()`` argument/output/temp footprint of one AOT-compiled
(bucket, batch, mode) program, cross-checked against an analytic model
of the consensus conv4d stack (the paper's k^4-kernel math). The
analytic side is a deliberate LOWER bound of the whole program (the
backbone, correlation and match extraction ride on top), so the
honesty flag is one-directional: ``model_ok`` means "the analytic
consensus cost does not exceed what XLA measured for the whole
program" — the same publish-the-check posture as bench's ``scale_ok``.

Producers: ``serving.engine.MatchEngine.warmup`` cards every program it
precompiles; ``ops.autotune.autotune`` cards the winning plan and
persists the card next to the strategy cache (the sidecar), so a cached
plan carries the cost signature that explains *why* it won. Consumers:
``tools/program_cards.py`` (roofline table, diff, ``--strict``
regression gate) and the ``program_card`` runlog events + labeled
``engine.costcard.*`` gauges.

HBM accounting rides here too: ``device.hbm.*`` gauges polled lazily
(rate-limited, no thread — the ``SloEngine.maybe_evaluate`` pattern)
from ``/healthz`` and ``/metrics`` reads, plus the warmup headroom
check comparing the warmed programs' summed temp bytes against the
device limit.

Everything is fenced: a backend without cost/memory analysis (or with
``memory_stats() is None`` — CPU) degrades to partial cards and absent
gauges, never to a serving failure. ``NCNET_COSTCARDS=0`` disables
capture entirely.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .events import event
from .metrics import gauge

#: Sidecar basename, written next to the autotune strategy cache
#: (``trained_models/consensus_autotune.json`` by default).
SIDECAR_BASENAME = "program_cards.json"

SIDECAR_VERSION = 1

#: ``model_ok`` tolerance: the analytic consensus lower bound may
#: exceed the XLA total by at most this factor before the card calls
#: itself out (covers FLOP-counting slack between XLA's HLO accounting
#: and the textbook 2*MAC convolution formula).
MODEL_TOL = 1.05


def enabled() -> bool:
    """Cost-card capture gate: on by default, ``NCNET_COSTCARDS=0`` off."""
    return os.environ.get("NCNET_COSTCARDS", "1") != "0"


# --- AOT capture ------------------------------------------------------


def _cost_dict(compiled) -> Dict[str, float]:
    """Normalize ``compiled.cost_analysis()`` (dict, or list of dicts on
    older jax) into one flat {str: float} map."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {k: float(v) for k, v in dict(ca).items()
            if isinstance(v, (int, float))}


def _memory_dict(compiled) -> Dict[str, Optional[int]]:
    ma = compiled.memory_analysis()
    out = {}
    for field in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes"):
        v = getattr(ma, field, None)
        short = field.replace("_size_in_bytes", "_bytes")
        out[short] = int(v) if v is not None else None
    return out


def aot_capture(jitted, *args) -> Optional[dict]:
    """Lower+compile ``jitted(*args)`` ahead of time and read its cost
    and memory analyses. Returns ``{"xla": {...}, "memory": {...}}``
    with whichever halves the backend supports, or None when even the
    compile fails (the card is then skipped, never fatal — the program
    itself already compiled through the normal jit path)."""
    try:
        compiled = jitted.lower(*args).compile()
    except Exception:  # noqa: BLE001 — capture must never break warmup
        return None
    out: dict = {"xla": None, "memory": None}
    try:
        ca = _cost_dict(compiled)
        out["xla"] = {
            "flops": ca.get("flops"),
            "bytes_accessed": ca.get("bytes accessed"),
            "transcendentals": ca.get("transcendentals"),
        }
    except Exception:  # noqa: BLE001 — backend without cost_analysis
        pass
    try:
        out["memory"] = _memory_dict(compiled)
    except Exception:  # noqa: BLE001 — backend without memory_analysis
        pass
    if out["xla"] is None and out["memory"] is None:
        return None
    return out


# --- the analytic consensus model -------------------------------------


def consensus_layers(params) -> List[Tuple[Tuple[int, ...], int, int]]:
    """``[(kernel_dims, cin, cout)]`` from a neigh-consensus params list
    (``{'weight': [k,k,k,k,cin,cout], ...}`` per layer)."""
    out = []
    for layer in params:
        shape = tuple(int(d) for d in layer["weight"].shape)
        out.append((shape[:4], shape[4], shape[5]))
    return out


def layers_from_config(config) -> List[Tuple[Tuple[int, ...], int, int]]:
    """The same layer spec derived from an NCNetConfig (no params in
    hand — the serving warmup path)."""
    out, cin = [], 1
    for k, cout in zip(config.ncons_kernel_sizes, config.ncons_channels):
        out.append(((int(k),) * 4, cin, int(cout)))
        cin = int(cout)
    return out


def _avg_taps(k: int, g: int) -> float:
    """Mean in-bounds tap count per output position of a SAME-padded
    1-D convolution, kernel ``k`` over ``g`` positions — the exact
    valid-MAC average once border overhang is excluded."""
    k, g = int(k), int(g)
    if g <= 0:
        return float(k)
    half = (k - 1) // 2
    total = 0
    for i in range(g):
        total += min(i + half, g - 1) - max(i - half, 0) + 1
    return total / g


def consensus_model(layers, cells: int, *, symmetric: bool,
                    dtype_bytes: int, batch: int = 1,
                    applications: int = 1, kind: str = "dense",
                    cp_rank: int = 0, dims=None) -> dict:
    """Textbook cost of the consensus stack over ``cells`` 4-D positions.

    Per dense layer: ``2 * cells * prod(kernel) * cin * cout`` FLOPs (2
    per MAC) and ``cells * (cin + cout) * dtype_bytes`` activation
    traffic (weights are negligible at these channel counts). When the
    4-D grid ``dims`` is given, ``prod(kernel)`` tightens to the exact
    valid-MAC average per dim (XLA counts no border-overhang MACs, and
    at smoke-size grids the overhang is a >2x overcount — without the
    correction ``model_ok`` fails honest small-shape cards). The
    algebraic arms (ops/cp4d.py) do fundamentally less arithmetic, so
    the lower bound must be ARM-AWARE or ``model_ok`` would correctly
    call a CP card a lie (dense bound > measured CP FLOPs):

      * ``kind='cp'``: the rank-R channel mixes alone,
        ``2 * cells * R * cin * cout`` with R clamped to the tap count
        — an honest floor below the separable-stage cost (XLA's HLO
        accounting of the fused per-axis shift-add stages lands well
        under the textbook 1-D-conv figure, same slack as fft below).
      * ``kind='fft'``: the pointwise spectral product alone,
        ``2 * cells * cin * cout`` — an honest floor below the
        transform cost (FLOP-counting FFTs would over-claim vs XLA's
        HLO accounting of fused twiddle stages).

    ``symmetric`` doubles everything (the A<->B-transposed second
    branch); ``batch``/``applications`` scale for scanned pair stacks
    and repeated window applies. Deliberately a lower bound: no
    bias/ReLU FLOPs, no layout copies — see module docstring for why
    that is the honest direction."""
    flops = 0.0
    byts = 0.0
    for kernel, cin, cout in layers:
        k4 = 1
        for k in kernel:
            k4 *= int(k)
        if kind == "cp":
            r = min(max(int(cp_rank), 1), k4)
            flops += 2.0 * cells * r * cin * cout
        elif kind == "fft":
            flops += 2.0 * cells * cin * cout
        else:
            taps = float(k4)
            if dims is not None and len(dims) == len(kernel):
                taps = 1.0
                for k, g in zip(kernel, dims):
                    taps *= _avg_taps(k, g)
            flops += 2.0 * cells * taps * cin * cout
        byts += float(cells) * (cin + cout) * dtype_bytes
    mult = (2 if symmetric else 1) * max(int(batch), 1) \
        * max(int(applications), 1)
    return {
        "consensus_flops": flops * mult,
        "consensus_bytes": byts * mult,
        "cells": int(cells),
        "layers": len(layers),
        "symmetric": bool(symmetric),
        "kind": str(kind),
        "cp_rank": int(cp_rank),
        "applications": int(applications) * max(int(batch), 1),
    }


def model_check(model: Optional[dict], xla: Optional[dict]) -> Optional[bool]:
    """``model_ok``: analytic consensus lower bound <= measured XLA
    total (within MODEL_TOL). None when either side is missing."""
    if not model or not xla:
        return None
    measured = xla.get("flops")
    if measured is None or measured <= 0:
        return None
    return model["consensus_flops"] <= measured * MODEL_TOL


# --- card assembly + emission -----------------------------------------


def card_key(program: str, q_shape, p_shape, batch: int, mode: str) -> str:
    qs = "x".join(str(int(d)) for d in q_shape)
    ps = "x".join(str(int(d)) for d in p_shape)
    return f"{program}|q{qs}|p{ps}|b{int(batch)}|{mode}"


def make_card(*, program: str, q_shape, p_shape, batch: int, mode: str,
              captured: dict, model: Optional[dict],
              backend: Optional[str] = None) -> dict:
    xla = captured.get("xla")
    card = {
        "key": card_key(program, q_shape, p_shape, batch, mode),
        "program": program,
        "q_shape": [int(d) for d in q_shape],
        "p_shape": [int(d) for d in p_shape],
        "batch": int(batch),
        "mode": mode,
        "backend": backend,
        "xla": xla,
        "memory": captured.get("memory"),
        "model": model,
        "model_ok": model_check(model, xla),
    }
    flops = (xla or {}).get("flops")
    byts = (xla or {}).get("bytes_accessed")
    if flops and byts:
        # Arithmetic intensity — the roofline x-axis
        # (tools/program_cards.py places it against the chip ridge).
        card["flops_per_byte"] = flops / byts
    return card


def emit_card(card: dict, labels=None) -> None:
    """One ``program_card`` runlog event + the labeled
    ``engine.costcard.*`` gauges for the card's hot numbers."""
    event("program_card", **card)
    lbls = dict(labels or {})
    lbls.update({
        "program": card["program"],
        "bucket": "x".join(str(d) for d in card["q_shape"]) + "-"
        + "x".join(str(d) for d in card["p_shape"]),
        "batch": str(card["batch"]),
        "mode": card["mode"],
    })
    xla = card.get("xla") or {}
    mem = card.get("memory") or {}
    if xla.get("flops") is not None:
        gauge("engine.costcard.flops", labels=lbls).set(xla["flops"])
    if xla.get("bytes_accessed") is not None:
        gauge("engine.costcard.bytes_accessed",
              labels=lbls).set(xla["bytes_accessed"])
    if mem.get("temp_bytes") is not None:
        gauge("engine.costcard.temp_bytes",
              labels=lbls).set(mem["temp_bytes"])
    if card.get("model_ok") is not None:
        gauge("engine.costcard.model_ok",
              labels=lbls).set(1.0 if card["model_ok"] else 0.0)


# --- sidecar persistence ----------------------------------------------


def sidecar_path(cache_file: Optional[str]) -> Optional[str]:
    """Resolve the sidecar path next to a strategy-cache file.

    ``NCNET_COSTCARDS_PATH`` overrides (empty string disables);
    otherwise the sidecar is ``SIDECAR_BASENAME`` in the cache file's
    directory, and a disabled cache (None) disables the sidecar too —
    the sidecar only ever piggybacks on an explicitly consented write.
    """
    env = os.environ.get("NCNET_COSTCARDS_PATH")
    if env is not None:
        return env or None
    if not cache_file:
        return None
    return os.path.join(os.path.dirname(cache_file) or ".",
                        SIDECAR_BASENAME)


def load_cards(path: str) -> Dict[str, dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    return dict(data.get("cards") or {})


def save_cards(cards: Sequence[dict], path: str) -> str:
    """Merge ``cards`` into the sidecar keyed by card key (read-modify-
    write, rename-aside — the save_plan durability posture)."""
    data = {"version": SIDECAR_VERSION, "cards": load_cards(path)}
    for card in cards:
        data["cards"][card["key"]] = card
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


# --- HBM accounting ---------------------------------------------------


def device_memory_stats(device) -> Optional[dict]:
    """Fenced ``device.memory_stats()`` — None on backends that don't
    report (CPU), on no device, and on any backend error."""
    if device is None:
        return None
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — accounting never breaks serving
        return None
    return dict(stats) if stats else None


class HbmMonitor:
    """Lazy per-device HBM gauge poller.

    No thread: callers (the serving ``/healthz`` and ``/metrics``
    handlers) invoke :meth:`maybe_poll` on every read and the monitor
    rate-limits the actual ``memory_stats()`` calls behind
    ``min_interval_s`` — the exact ``SloEngine.maybe_evaluate``
    pattern, so a scrape storm cannot turn accounting into load.
    """

    def __init__(self, min_interval_s: float = 1.0):
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        # None = never polled; a 0.0 sentinel would alias boot time and
        # rate-limit the FIRST poll on hosts up less than min_interval_s
        # (time.monotonic() is boot-relative on Linux).
        self._last = None

    def maybe_poll(self, entries) -> bool:
        """``entries``: iterable of (device, labels). Returns True when
        a poll actually ran (rate-limit window open)."""
        now = time.monotonic()
        with self._lock:
            if (self._last is not None
                    and now - self._last < self.min_interval_s):
                return False
            self._last = now
        for device, labels in entries:
            stats = device_memory_stats(device)
            if not stats:
                continue
            if stats.get("bytes_in_use") is not None:
                gauge("device.hbm.bytes_in_use",
                      labels=labels).set(stats["bytes_in_use"])
            if stats.get("peak_bytes_in_use") is not None:
                gauge("device.hbm.peak_bytes",
                      labels=labels).set(stats["peak_bytes_in_use"])
            if stats.get("bytes_limit") is not None:
                gauge("device.hbm.limit_bytes",
                      labels=labels).set(stats["bytes_limit"])
        return True


#: Process-wide monitor (one device set per process; per-object labels
#: keep fleet replicas' series apart, like the metrics registry itself).
_HBM = HbmMonitor()


def poll_hbm(entries) -> bool:
    return _HBM.maybe_poll(entries)


def check_headroom(cards: Sequence[dict], device, labels=None,
                   stats: Optional[dict] = None) -> Optional[dict]:
    """Warmup headroom check: do the declared buckets' programs fit?

    Sums the warmed cards' temp bytes (the transient working set each
    program needs on top of its arguments) and compares against the
    device's ``bytes_limit``. Emits an ``hbm_headroom`` obs event
    either way; the caller surfaces ``ok=False`` as a degraded-healthz
    warning. ``NCNET_HBM_HEADROOM_STRICT=1`` upgrades a violation to a
    RuntimeError (refuse to serve a config that cannot fit). Returns
    the verdict dict, or None when the device doesn't report limits
    (CPU) or no card carried temp bytes."""
    if stats is None:
        stats = device_memory_stats(device)
    limit = (stats or {}).get("bytes_limit")
    if limit is None:
        return None
    temps = [c.get("memory", {}).get("temp_bytes") for c in cards
             if c.get("memory")]
    temps = [t for t in temps if t is not None]
    if not temps:
        return None
    verdict = {
        "ok": sum(temps) <= limit,
        "temp_bytes": int(sum(temps)),
        "limit_bytes": int(limit),
        "bytes_in_use": stats.get("bytes_in_use"),
        "programs": len(temps),
    }
    event("hbm_headroom", **verdict)
    if not verdict["ok"] and \
            os.environ.get("NCNET_HBM_HEADROOM_STRICT") == "1":
        raise RuntimeError(
            f"warmup headroom: declared buckets need "
            f"{verdict['temp_bytes']} temp bytes > device limit {limit}"
        )
    return verdict
