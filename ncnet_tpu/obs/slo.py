"""Declarative SLOs with multi-window burn-rate alerts and error budgets.

The DeadlineBatcher enforces per-request deadlines and the breaker
contains device outages, but nothing ACCOUNTS for them: how much of the
month's error budget did that 40-second breaker episode spend? This
module is the Google-SRE-workbook answer, sized for this repo:

* :class:`SloSpec` — one declarative objective. Two shapes:

  - **counter ratio**: ``good`` / ``total`` name the counters whose
    deltas define success (availability: responses vs requests-that-
    deserved-an-answer; deadline hit rate: responses vs responses +
    deadline_exceeded);
  - **latency threshold**: ``histogram`` + ``threshold_s`` count the
    observations at-or-under the threshold as good. Exact at bucket
    resolution: the effective threshold is the largest bucket bound
    <= ``threshold_s`` (the shared ladder, obs/metrics.DEFAULT_BUCKETS).

* :class:`SloEngine` — evaluates every spec against registry snapshots
  on an injectable clock. Burn rate = (bad fraction over a rolling
  window) / (1 - objective); the **multi-window rule** pages only when
  BOTH the fast window (default 5 min, threshold 14x) and the slow
  window (default 1 h, threshold 6x) burn hot — fast-only is noise,
  slow-only is too late (Google SRE workbook, ch. 5).

Paging is loud in every channel at once: ``slo.<name>.*`` gauges and a
``pages`` counter in the registry, an obs ``slo`` event per episode
edge, a ``/healthz`` budget field (serving/server.py), and — once per
episode, riding the flight recorder's per-reason cooldown — a
``slo-burn-<name>`` flight dump capturing the events that led in.

Windows hold (t, good, total) samples pruned to the slow window; the
30-day error budget runs on a coarser sample train (bounded at ~256
points) so a month of accounting costs kilobytes, not a sample per
scrape.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, Optional, Tuple, Union

from . import events as _events
from . import flight as _flight
from . import metrics as _metrics

Names = Union[str, Tuple[str, ...]]


@dataclass(frozen=True)
class SloSpec:
    """One service-level objective, counter-ratio or latency-threshold."""

    name: str
    objective: float                      # e.g. 0.999
    good: Optional[Names] = None          # counter name(s) counting good
    total: Optional[Names] = None         # counter name(s) counting all
    histogram: Optional[str] = None       # latency-mode histogram name
    threshold_s: Optional[float] = None   # latency-mode "good" bound
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn: float = 14.0               # page when BOTH windows exceed
    slow_burn: float = 6.0
    budget_window_s: float = 30 * 86400.0

    def __post_init__(self):
        if not (0.0 < self.objective < 1.0):
            raise ValueError(f"objective must be in (0, 1): {self.objective}")
        counter_mode = self.good is not None and self.total is not None
        latency_mode = (self.histogram is not None
                        and self.threshold_s is not None)
        if counter_mode == latency_mode:
            raise ValueError(
                f"SLO {self.name!r} needs exactly one of good+total "
                "counters or histogram+threshold_s")
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError("fast_window_s must be < slow_window_s")

    @property
    def budget_frac(self) -> float:
        return 1.0 - self.objective


def _as_names(names: Names) -> Tuple[str, ...]:
    return (names,) if isinstance(names, str) else tuple(names)


class SloEngine:
    """Evaluate :class:`SloSpec` s against registry snapshots over time.

    ``labels`` scopes which series count: a spec's counters/histogram
    match any series whose labels are a superset of the engine's (so a
    replica-labeled engine reads its own series, and an unlabeled one
    reads everything — summing children, which is what a whole-process
    SLO means).
    """

    def __init__(
        self,
        specs: Iterable[SloSpec],
        registry: Optional[_metrics.MetricsRegistry] = None,
        labels=None,
        clock: Callable[[], float] = time.monotonic,
        min_interval_s: float = 0.0,
        flight_dump: bool = True,
    ):
        self.specs = list(specs)
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.registry = registry or _metrics.default_registry()
        self.labels = dict(labels or {})
        self.clock = clock
        self.min_interval_s = float(min_interval_s)
        self.flight_dump = flight_dump
        self._samples = {s.name: deque() for s in self.specs}
        self._budget = {s.name: deque() for s in self.specs}
        self._paging = {s.name: False for s in self.specs}
        self._pages = {s.name: 0 for s in self.specs}
        self._last_results: Dict[str, dict] = {}
        self._last_eval: Optional[float] = None

    # -- snapshot readers -------------------------------------------------

    def _matches(self, lbls: Dict[str, str]) -> bool:
        return all(lbls.get(k) == v for k, v in self.labels.items())

    def _sum_counters(self, snap: dict, names: Names) -> float:
        wanted = _as_names(names)
        total = 0.0
        for series, v in (snap.get("counters") or {}).items():
            name, lbls = _metrics.parse_series(series)
            if name in wanted and self._matches(lbls):
                total += v
        return total

    def _hist_good_total(self, snap: dict, spec: SloSpec
                         ) -> Tuple[float, float]:
        good = total = 0.0
        for series, h in (snap.get("histograms") or {}).items():
            name, lbls = _metrics.parse_series(series)
            if name != spec.histogram or not self._matches(lbls):
                continue
            total += float(h.get("count") or 0)
            at_or_under = 0.0
            for le, cum in h.get("buckets") or []:
                if le <= spec.threshold_s:
                    at_or_under = cum
                else:
                    break
            good += at_or_under
        return good, total

    def _read(self, snap: dict, spec: SloSpec) -> Tuple[float, float]:
        if spec.histogram is not None:
            return self._hist_good_total(snap, spec)
        return (self._sum_counters(snap, spec.good),
                self._sum_counters(snap, spec.total))

    # -- window math ------------------------------------------------------

    @staticmethod
    def _window_bad_frac(samples, now: float, window_s: float,
                         g_now: float, t_now: float) -> float:
        """Bad fraction over [now - window_s, now].

        Baseline = the latest sample at or before the window start; a
        window that predates the engine uses the oldest sample (burn
        over available history — an engine younger than its window
        reports what it can see rather than nothing).
        """
        base = None
        for t, g, tot in samples:
            if t <= now - window_s:
                base = (g, tot)
            else:
                break
        if base is None:
            base = (samples[0][1], samples[0][2]) if samples else (g_now,
                                                                   t_now)
        d_total = t_now - base[1]
        d_good = g_now - base[0]
        if d_total <= 0:
            return 0.0
        return max(d_total - d_good, 0.0) / d_total

    # -- evaluation -------------------------------------------------------

    def maybe_evaluate(self, snapshot: Optional[dict] = None
                       ) -> Dict[str, dict]:
        """Rate-limited :meth:`evaluate` — the /healthz and /metrics
        hook, so a scrape storm cannot turn SLO math into load."""
        now = self.clock()
        if (self._last_eval is not None and self.min_interval_s > 0
                and now - self._last_eval < self.min_interval_s):
            return self._last_results
        return self.evaluate(snapshot)

    def evaluate(self, snapshot: Optional[dict] = None) -> Dict[str, dict]:
        """One evaluation pass: sample, burn, budget, page edges."""
        now = self.clock()
        snap = snapshot if snapshot is not None else self.registry.snapshot()
        results: Dict[str, dict] = {}
        for spec in self.specs:
            good, total = self._read(snap, spec)
            samples = self._samples[spec.name]
            samples.append((now, good, total))
            while samples and samples[0][0] < now - spec.slow_window_s:
                samples.popleft()
            # Budget train: coarse (<= ~256 live points) so 30 days of
            # accounting stays bounded no matter the scrape rate.
            budget = self._budget[spec.name]
            step = spec.budget_window_s / 256.0
            if not budget or now - budget[-1][0] >= step:
                budget.append((now, good, total))
            while len(budget) > 2 and budget[1][0] < now - spec.budget_window_s:
                budget.popleft()

            burn_fast = self._window_bad_frac(
                samples, now, spec.fast_window_s, good, total
            ) / spec.budget_frac
            burn_slow = self._window_bad_frac(
                samples, now, spec.slow_window_s, good, total
            ) / spec.budget_frac

            b0 = budget[0]
            b_total = total - b0[2]
            b_bad = max(b_total - (good - b0[1]), 0.0)
            allowed = spec.budget_frac * b_total
            if allowed > 0:
                remaining = 1.0 - b_bad / allowed
            else:
                remaining = 1.0
            # Clamp at zero: "budget exhausted" is the floor the
            # balancer-facing readout reports — how far PAST empty the
            # window burned is burn-rate territory, and a negative
            # fraction reads as a telemetry bug to consumers.
            remaining = max(min(remaining, 1.0), 0.0)

            paging = (burn_fast >= spec.fast_burn
                      and burn_slow >= spec.slow_burn)
            was = self._paging[spec.name]
            self._paging[spec.name] = paging
            if paging and not was:
                self._pages[spec.name] += 1
                self.registry.counter(f"slo.{spec.name}.pages",
                                      labels=self.labels).inc()
                _events.event("slo", slo=spec.name, state="page_start",
                              burn_fast=round(burn_fast, 4),
                              burn_slow=round(burn_slow, 4),
                              budget_remaining_frac=round(remaining, 6))
                if self.flight_dump:
                    # One dump per episode (this edge fires once per
                    # episode) AND per-reason cooldown underneath, so a
                    # flapping alert cannot fill a disk (obs/flight.py).
                    _flight.dump(f"slo-burn-{spec.name}")
            elif was and not paging:
                _events.event("slo", slo=spec.name, state="page_end",
                              burn_fast=round(burn_fast, 4),
                              burn_slow=round(burn_slow, 4),
                              budget_remaining_frac=round(remaining, 6))

            for suffix, value in (
                ("burn_fast", burn_fast),
                ("burn_slow", burn_slow),
                ("budget_remaining_frac", remaining),
                ("paging", 1.0 if paging else 0.0),
            ):
                self.registry.gauge(f"slo.{spec.name}.{suffix}",
                                    labels=self.labels).set(value)

            results[spec.name] = {
                "objective": spec.objective,
                "good": good,
                "total": total,
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "fast_window_s": spec.fast_window_s,
                "slow_window_s": spec.slow_window_s,
                "paging": paging,
                "pages": self._pages[spec.name],
                "budget_remaining_frac": round(remaining, 6),
            }
        self._last_results = results
        self._last_eval = now
        return results

    @property
    def paging(self) -> bool:
        """True while ANY spec is in a page episode."""
        return any(self._paging.values())


def default_serving_slos(
    availability: float = 0.999,
    deadline_hit: float = 0.99,
    p99_target_s: float = 0.5,
    fast_window_s: float = 300.0,
    slow_window_s: float = 3600.0,
) -> Tuple[SloSpec, ...]:
    """The serving front end's three standing objectives.

    * ``availability`` — responses vs requests the server owed an
      answer: 200s vs 200s + 500s + 504s. Client errors (400) and
      load-shed 503s are excluded — a shed request was answered
      honestly and retried; counting it would make admission control
      look like an outage.
    * ``deadline_hit`` — of requests that ran, how many beat their
      deadline (the DeadlineBatcher's contract, measured).
    * ``latency_p99`` — fraction of requests at or under the p99
      target; exact at the shared bucket ladder's resolution.
    """
    win = dict(fast_window_s=fast_window_s, slow_window_s=slow_window_s)
    return (
        SloSpec("availability", availability,
                good="serving.responses",
                total=("serving.responses", "serving.errors",
                       "serving.deadline_exceeded"),
                **win),
        SloSpec("deadline_hit", deadline_hit,
                good="serving.responses",
                total=("serving.responses", "serving.deadline_exceeded"),
                **win),
        SloSpec("latency_p99", 0.99,
                histogram="serving.e2e_latency_s",
                threshold_s=p99_target_s,
                **win),
    )
