"""Cross-replica metric aggregation: N snapshots/scrapes -> one fleet view.

The fleet primitive for ROADMAP item 1 (multi-replica serving): each
replica labels its hot-path series with ``replica="<id>"``
(obs/metrics.py), and this module merges any number of registry
snapshots — or live ``GET /metrics`` scrapes — into a single view:

* **counters** sum across replicas;
* **histograms** merge at bucket resolution: per-bucket deltas add, so
  the fleet p50/p95/p99 are EXACT at the shared ladder's resolution
  (the same :func:`~ncnet_tpu.obs.metrics.bucket_quantile` math a local
  histogram uses — not an average of per-replica percentiles, which
  would be statistically meaningless);
* **gauges** keep per-replica values plus min/max/mean (a queue depth
  summed across replicas is a lie; the dispatcher wants the spread).

Series identity: the ``replica`` label IS the identity. Two sources
reporting the same (name, labels, replica) series are the same series
observed twice — last wins, no double count (this also makes merging
two servers that share one process registry correct, the tier-1 demo's
shape). Series WITHOUT a replica label are treated per-source.

Everything here is stdlib-only and host-side: the dashboard
(tools/fleet_status.py) and tests consume it without jax.
"""

from __future__ import annotations

import math
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

from .metrics import (
    _LABEL_RE,
    _unescape_label_value,
    bucket_quantile,
    format_series,
    parse_series,
)

#: The label that names a series' owning replica (obs/metrics.py
#: replica_labels / the serving --replica_id identity).
REPLICA_LABEL = "replica"


def _merge_histograms(entries: List[dict]) -> dict:
    """Merge snapshot-form histogram entries exactly, at bucket resolution.

    Each entry carries ``buckets`` as sparse cumulative ``[le, cum]``
    pairs (obs/metrics.Histogram.snapshot); cumulative counts convert
    to per-bucket deltas, deltas add across entries, and the merged
    quantiles run the same bucket interpolation a local histogram uses.
    """
    deltas: Dict[float, float] = {}
    inf = 0.0
    count = 0.0
    total_sum = 0.0
    mn = mx = last = None
    for h in entries:
        c = float(h.get("count") or 0)
        count += c
        total_sum += float(h.get("sum") or 0.0)
        if h.get("min") is not None:
            mn = h["min"] if mn is None else min(mn, h["min"])
        if h.get("max") is not None:
            mx = h["max"] if mx is None else max(mx, h["max"])
        if h.get("last") is not None:
            last = h["last"]
        prev = 0.0
        for le, cum in h.get("buckets") or []:
            deltas[float(le)] = deltas.get(float(le), 0.0) + (cum - prev)
            prev = cum
        inf += c - prev  # observations above the last finite bound
    bounds = sorted(deltas)
    counts = [deltas[b] for b in bounds] + [inf]

    def q(p):
        return bucket_quantile(bounds, counts, count, p,
                               lo_clamp=mn, hi_clamp=mx)

    cum, buckets = 0.0, []
    for b in bounds:
        cum += deltas[b]
        buckets.append([b, cum])
    return {
        "count": count,
        "sum": total_sum,
        "mean": (total_sum / count) if count else None,
        "min": mn,
        "max": mx,
        "last": last,
        "p50": q(0.50),
        "p95": q(0.95),
        "p99": q(0.99),
        "buckets": buckets,
    }


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Merge N registry snapshots (or parsed scrapes) into a fleet view.

    Returns::

        {"n_sources": N, "replicas": [...sorted replica ids...],
         "counters":   {series: summed value},
         "gauges":     {series: {"min","max","mean","n",
                                 "per_replica": {id: value}}},
         "histograms": {series: merged entry (snapshot shape)},
         "per_replica": {id: {"counters": {...}, "gauges": {...},
                              "histograms": {...}}}}

    Series keys in the output have the ``replica`` label STRIPPED (it
    became the aggregation dimension); all other labels survive. A
    source with no replica-labeled series contributes under the
    synthetic id ``source<i>``.
    """
    snaps = list(snaps)
    stores = {"counters": {}, "gauges": {}, "histograms": {}}
    replicas = set()
    for i, snap in enumerate(snaps):
        for kind, store in stores.items():
            for series, val in (snap.get(kind) or {}).items():
                name, lbls = parse_series(series)
                rid = lbls.pop(REPLICA_LABEL, None)
                rest = tuple(sorted(lbls.items()))
                if rid is None:
                    ident = f"source{i}"
                else:
                    ident = rid
                    replicas.add(rid)
                # Same (name, labels, replica) from two sources is ONE
                # series observed twice: last wins, no double count.
                store.setdefault((name, rest), {})[ident] = val

    out = {
        "n_sources": len(snaps),
        "replicas": sorted(replicas),
        "counters": {},
        "gauges": {},
        "histograms": {},
        "per_replica": {},
    }

    def per_replica(ident):
        return out["per_replica"].setdefault(
            ident, {"counters": {}, "gauges": {}, "histograms": {}})

    for (name, rest), by_id in sorted(stores["counters"].items()):
        key = format_series(name, dict(rest))
        out["counters"][key] = sum(by_id.values())
        for ident, v in sorted(by_id.items()):
            per_replica(ident)["counters"][key] = v

    for (name, rest), by_id in sorted(stores["gauges"].items()):
        key = format_series(name, dict(rest))
        vals = {i: v for i, v in by_id.items() if v is not None}
        entry = {"n": len(vals), "per_replica": dict(sorted(vals.items()))}
        if vals:
            entry["min"] = min(vals.values())
            entry["max"] = max(vals.values())
            entry["mean"] = sum(vals.values()) / len(vals)
        out["gauges"][key] = entry
        for ident, v in sorted(vals.items()):
            per_replica(ident)["gauges"][key] = v

    for (name, rest), by_id in sorted(stores["histograms"].items()):
        key = format_series(name, dict(rest))
        out["histograms"][key] = _merge_histograms(list(by_id.values()))
        for ident, h in sorted(by_id.items()):
            per_replica(ident)["histograms"][key] = h
    return out


# -- Prometheus text exposition -> snapshot form -------------------------

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_sample(line: str) -> Optional[Tuple[str, Dict[str, str], float]]:
    # OpenMetrics exemplar suffix (` # {trace_id="..."} value ts` on
    # histogram _bucket lines — obs/metrics.py) is scrape metadata, not
    # part of the sample: strip it before the label/value split, or the
    # rpartition("}") below would grab the exemplar's closing brace.
    cut = line.find(" # {")
    if cut != -1:
        line = line[:cut]
    rest = line
    name, labels = rest, {}
    if "{" in line:
        name, _, rest = line.partition("{")
        body, _, rest = rest.rpartition("}")
        labels = {k: _unescape_label_value(v)
                  for k, v in _LABEL_RE.findall(body)}
    else:
        name, _, rest = line.partition(" ")
    try:
        value = float(rest.strip())
    except ValueError:
        return None
    return name.strip(), labels, value


def parse_prometheus_text(text: str) -> dict:
    """Parse one ``GET /metrics`` body back into registry-snapshot form.

    The inverse of ``MetricsRegistry.render_text`` (modulo the dotted->
    underscore name sanitization, which is not invertible: scraped
    snapshots carry prom-style names, so only merge scrapes with
    scrapes). ``_total`` counters lose the suffix marker back into the
    counter map; histogram ``_bucket``/``_sum``/``_count`` lines and the
    ``_min``/``_max``/``_last`` companion gauges fold back into one
    histogram entry per labeled series.
    """
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        s = _parse_sample(line)
        if s is not None:
            samples.append(s)

    hist_families = {n for n, t in types.items() if t == "histogram"}
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    raw_hists: Dict[Tuple[str, tuple], dict] = {}

    def hist_entry(base, labels):
        key = (base, tuple(sorted(labels.items())))
        return raw_hists.setdefault(
            key, {"buckets": {}, "count": 0.0, "sum": 0.0})

    for name, labels, value in samples:
        if types.get(name) == "counter" and name.endswith("_total"):
            out["counters"][format_series(name[:-6], labels)] = value
            continue
        matched = False
        for suffix in _HIST_SUFFIXES:
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and base in hist_families:
                if suffix == "_bucket":
                    le = labels.pop("le", None)
                    if le is not None:
                        b = float(le)
                        if math.isfinite(b):
                            hist_entry(base, labels)["buckets"][b] = value
                elif suffix == "_sum":
                    hist_entry(base, labels)["sum"] = value
                else:
                    hist_entry(base, labels)["count"] = value
                matched = True
                break
        if matched:
            continue
        for suffix in ("_min", "_max", "_last"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and base in hist_families:
                hist_entry(base, labels)[suffix[1:]] = value
                matched = True
                break
        if not matched:
            out["gauges"][format_series(name, labels)] = value

    for (base, lbls), raw in sorted(raw_hists.items()):
        count = raw.get("count", 0.0)
        bounds = sorted(raw["buckets"])
        # Cumulative finite-bucket lines -> sparse [le, cum] pairs
        # (drop repeats: the exposition elides empties, but a merged
        # upstream may not have).
        prev, buckets = 0.0, []
        for b in bounds:
            cum = raw["buckets"][b]
            if cum != prev:
                buckets.append([b, cum])
            prev = cum
        entry = {
            "count": count,
            "sum": raw.get("sum", 0.0),
            "mean": (raw.get("sum", 0.0) / count) if count else None,
            "min": raw.get("min"),
            "max": raw.get("max"),
            "last": raw.get("last"),
            "buckets": buckets,
        }
        deltas, p = [], 0.0
        for b in bounds:
            deltas.append(raw["buckets"][b] - p)
            p = raw["buckets"][b]
        deltas.append(count - p)
        for qname, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            entry[qname] = bucket_quantile(
                bounds, deltas, count, q,
                lo_clamp=entry["min"], hi_clamp=entry["max"])
        out["histograms"][format_series(base, dict(lbls))] = entry
    return out


def scrape(url: str, timeout_s: float = 5.0) -> dict:
    """Fetch one replica's ``/metrics`` and parse it to snapshot form."""
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        body = resp.read().decode("utf-8", "replace")
    return parse_prometheus_text(body)


def fleet_view(urls: Iterable[str], timeout_s: float = 5.0) -> dict:
    """Scrape every url and merge: the dashboard's one-call primitive.

    Unreachable replicas do not fail the view — they land in
    ``errors`` (url -> reason) and the merge covers the rest; a fleet
    view that dies with its least healthy member is useless exactly
    when it matters.
    """
    snaps, errors, sources = [], {}, []
    for url in urls:
        try:
            snaps.append(scrape(url, timeout_s=timeout_s))
            sources.append(url)
        except Exception as exc:  # noqa: BLE001 — per-source isolation
            errors[url] = f"{type(exc).__name__}: {exc}"
    view = merge_snapshots(snaps)
    view["sources"] = sources
    view["errors"] = errors
    return view
