"""Tail-latency exemplars: slowest-request reservoirs + slow dumps.

The histogram side lives in obs/metrics.py (``Histogram.observe``
accepts a ``trace_id`` and ``render_text`` appends the OpenMetrics
exemplar suffix to ``_bucket`` lines); this module owns the request
side: a bounded per-endpoint reservoir of the slowest requests seen,
and the rate-limited flight dump for requests breaching the SLO p99
target — so a tail spike always leaves a full span tree behind, not
just a histogram bump.

The dump reason is ``slow-exemplar-<endpoint>`` and rides the flight
recorder's per-reason cooldown (obs/flight.py): a burst of slow
requests produces exactly one dump per cooldown window, never a dump
storm on top of an already-slow replica.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional

from . import flight
from .events import event
from .metrics import counter

#: Slowest requests kept per endpoint (a tail forensics working set,
#: not a log — the run log has every request event).
RESERVOIR_SIZE = 16


class SlowReservoir:
    """Bounded per-endpoint reservoir of the slowest observations.

    A min-heap of (dur_s, seq, record) per endpoint: offering a new
    observation evicts the fastest member once the reservoir is full,
    so membership is exactly "the N slowest seen". Thread-safe — the
    serving handler threads offer concurrently.
    """

    def __init__(self, size: int = RESERVOIR_SIZE):
        self.size = int(size)
        self._lock = threading.Lock()
        self._heaps: Dict[str, list] = {}
        self._seq = 0

    def offer(self, endpoint: str, dur_s: float,
              trace_id: Optional[str], **meta) -> None:
        rec = {"endpoint": endpoint, "dur_s": float(dur_s),
               "trace_id": trace_id, "t_wall": time.time(), **meta}
        with self._lock:
            heap = self._heaps.setdefault(endpoint, [])
            self._seq += 1
            item = (float(dur_s), self._seq, rec)
            if len(heap) < self.size:
                heapq.heappush(heap, item)
            elif item > heap[0]:
                heapq.heapreplace(heap, item)

    def clear(self) -> None:
        with self._lock:
            self._heaps.clear()

    def snapshot(self, endpoint: Optional[str] = None) -> List[dict]:
        """Slowest-first records for one endpoint (or all)."""
        with self._lock:
            if endpoint is not None:
                items = list(self._heaps.get(endpoint, ()))
            else:
                items = [i for h in self._heaps.values() for i in h]
        return [rec for _, _, rec in sorted(items, reverse=True)]


#: Process-wide reservoir (per-object labels are already inside the
#: offered records via the endpoint name; tests build private ones).
_RESERVOIR = SlowReservoir()


def reservoir() -> SlowReservoir:
    return _RESERVOIR


def observe_request(endpoint: str, dur_s: float, trace_id: Optional[str],
                    threshold_s: Optional[float] = None,
                    labels=None) -> Optional[str]:
    """Book one finished request into the tail machinery.

    Always feeds the reservoir; when ``threshold_s`` is set and
    breached, emits a ``slow_request`` event (carrying the trace_id —
    it lands in the flight ring alongside the request's spans), bumps
    ``serving.slow_requests`` and triggers the rate-limited
    ``slow-exemplar-<endpoint>`` dump. Returns the dump path when a
    dump was actually written (None when suppressed by cooldown or not
    slow)."""
    _RESERVOIR.offer(endpoint, dur_s, trace_id)
    if threshold_s is None or dur_s <= threshold_s:
        return None
    counter("serving.slow_requests", labels=labels).inc()
    event("slow_request", endpoint=endpoint, trace_id=trace_id,
          e2e_s=round(float(dur_s), 6), threshold_s=float(threshold_s))
    return flight.dump(f"slow-exemplar-{endpoint}")
