"""Always-on bounded flight recorder: the last N events, dumpable.

The run log (obs/events.py) is opt-in — library code's events vanish
unless an entry point called ``init_run``. That is the right posture
for normal operation (unit tests must not grow log files), but it is
exactly wrong at triage time: the hangs ``SIGALRM`` cannot reach
(wedged C extension, stuck device dispatch) and the crashes that never
opened a run are the ones where "what happened in the last few
seconds" matters most.

This module keeps a process-global in-memory ring of the most recent
events — every ``obs.event``/span record lands here whether or not a
run is open — and dumps it to a JSONL file when something goes wrong:

* ``obs.Watchdog`` dumps just before its hard ``os._exit`` — the ring
  is the only record of what the process was doing when it wedged;
* ``obs.Heartbeat`` dumps at the start of each stall episode — the
  events *leading into* the stall, captured while the process is still
  alive to write them;
* the chained ``sys.excepthook`` / ``threading.excepthook`` installed
  by ``obs.events._install_exit_hooks`` dump on unhandled exceptions.

The ring is bounded (``NCNET_FLIGHT_EVENTS``, default 512 records) and
recording is a lock + deque append — cheap enough for per-request hot
paths. Dumps are rate-limited per reason so a flapping stall cannot
fill a disk.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

#: Dump files: ``flight-<reason>-<stamp>.jsonl`` in the first of
#: ``NCNET_FLIGHT_DIR``, the active run log's directory, or a
#: ``flight/`` subdir of cwd (never bare cwd).
_DUMP_PREFIX = "flight"

#: Minimum seconds between dumps for one reason (flap guard).
_DUMP_COOLDOWN_S = 30.0


def _capacity() -> int:
    try:
        return max(int(os.environ.get("NCNET_FLIGHT_EVENTS", "512")), 16)
    except ValueError:
        return 512


class FlightRecorder:
    """Bounded ring of recent event records + JSONL dump."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity or _capacity()
        self._buf = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._last_dump = {}  # reason -> monotonic time of last dump
        self.dumps = 0

    def record(self, rec: dict) -> None:
        """Append one event record (a plain dict; never raises)."""
        try:
            with self._lock:
                self._buf.append(rec)
        except Exception:
            pass  # telemetry must never take the caller down

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._last_dump.clear()

    def _dump_dir(self) -> str:
        env = os.environ.get("NCNET_FLIGHT_DIR")
        if env:
            return env
        # Next to the active run log, when one is open.
        try:
            from . import events

            run = events.get_run()
            if getattr(run, "path", None):
                return os.path.dirname(os.path.abspath(run.path)) or "."
        except Exception:
            pass
        # Last resort: a flight/ subdir of the CWD — NEVER the bare CWD,
        # which litters whatever directory the process happened to start
        # in (dump() creates the dir).
        return os.path.join(".", "flight")

    def dump(self, reason: str, directory: Optional[str] = None,
             force: bool = False) -> Optional[str]:
        """Write the ring to ``flight-<reason>-<stamp>.jsonl``; returns
        the path, or None (empty ring, cooldown, or unwritable dir —
        a triage helper must never crash the process it is triaging).
        """
        now = time.monotonic()
        with self._lock:
            if not self._buf:
                return None
            last = self._last_dump.get(reason)
            if not force and last is not None \
                    and now - last < _DUMP_COOLDOWN_S:
                return None
            self._last_dump[reason] = now
            records = list(self._buf)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in reason
        ) or "unknown"
        stamp = time.strftime("%Y%m%d-%H%M%S")
        d = directory or self._dump_dir()
        path = os.path.join(
            d, f"{_DUMP_PREFIX}-{safe_reason}-{stamp}-{os.getpid()}.jsonl"
        )
        try:
            os.makedirs(d, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                header = {
                    "event": "flight_dump",
                    "reason": reason,
                    "t_wall": time.time(),
                    "pid": os.getpid(),
                    "n_records": len(records),
                    "capacity": self.capacity,
                }
                fh.write(json.dumps(header, default=str) + "\n")
                for rec in records:
                    fh.write(json.dumps(rec, default=str) + "\n")
        except OSError:
            return None
        with self._lock:
            self.dumps += 1
        return path


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    """The process-global flight recorder."""
    return _RECORDER


def record(rec: dict) -> None:
    _RECORDER.record(rec)


def dump(reason: str, directory: Optional[str] = None,
         force: bool = False) -> Optional[str]:
    return _RECORDER.dump(reason, directory=directory, force=force)


_hooks_installed = False
_hooks_lock = threading.Lock()


def install_excepthooks() -> None:
    """Chain sys/threading excepthooks to dump the ring on unhandled
    exceptions; installed once (idempotent), called from
    ``obs.events._install_exit_hooks``."""
    global _hooks_installed
    with _hooks_lock:
        if _hooks_installed:
            return
        _hooks_installed = True

    import sys

    prev_sys = sys.excepthook

    def _sys_hook(exc_type, exc, tb):
        try:
            _RECORDER.dump(f"crash-{exc_type.__name__}")
        except Exception:
            pass
        prev_sys(exc_type, exc, tb)

    sys.excepthook = _sys_hook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        # SystemExit from a daemon thread is routine shutdown noise.
        if args.exc_type is not SystemExit:
            try:
                name = getattr(args.thread, "name", "thread")
                _RECORDER.dump(f"thread-{args.exc_type.__name__}-{name}")
            except Exception:
                pass
        prev_thread(args)

    threading.excepthook = _thread_hook
