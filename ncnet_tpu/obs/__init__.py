"""Run telemetry: structured event log, metrics registry, heartbeat.

See docs/OBSERVABILITY.md for the event schema and metric naming
convention. Quick tour::

    from ncnet_tpu import obs

    run = obs.init_run("eval_inloc", obs.default_log_path(out_dir,
                                                          "eval_inloc"),
                       args=args)
    obs.counter("eval_inloc.cache.hits").inc()
    with obs.span("consensus", sync=lambda: corr):
        ...
    run.flush_metrics(phase="matching")
    run.close("ok")

Library code calls ``obs.event``/``obs.span``/``obs.counter``
unconditionally — they no-op (or accumulate invisibly) unless an entry
point opened a run log.
"""

from . import (
    aggregate,
    costcards,
    exemplar,
    flight,
    quality,
    slo,
    trace,
    train_watch,
)
from .events import (
    NULL_RUN,
    RunLog,
    default_log_path,
    event,
    get_run,
    init_run,
    runlog_segments,
    span,
)
from .flight import FlightRecorder
from .heartbeat import Heartbeat, Watchdog
from .trace import SpanCtx, install_compile_telemetry
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    bucket_quantile,
    counter,
    default_registry,
    format_series,
    gauge,
    histogram,
    parse_series,
    render_text,
    replica_id,
    replica_labels,
    reset,
    set_build_info,
    set_replica_id,
    snapshot,
)
from .slo import SloEngine, SloSpec, default_serving_slos

__all__ = [
    "NULL_RUN",
    "RunLog",
    "default_log_path",
    "event",
    "get_run",
    "init_run",
    "runlog_segments",
    "span",
    "aggregate",
    "costcards",
    "exemplar",
    "flight",
    "quality",
    "slo",
    "trace",
    "train_watch",
    "SloEngine",
    "SloSpec",
    "default_serving_slos",
    "FlightRecorder",
    "SpanCtx",
    "install_compile_telemetry",
    "Heartbeat",
    "Watchdog",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "bucket_quantile",
    "counter",
    "default_registry",
    "format_series",
    "gauge",
    "histogram",
    "parse_series",
    "render_text",
    "replica_id",
    "replica_labels",
    "reset",
    "set_build_info",
    "set_replica_id",
    "snapshot",
]
