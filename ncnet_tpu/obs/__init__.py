"""Run telemetry: structured event log, metrics registry, heartbeat.

See docs/OBSERVABILITY.md for the event schema and metric naming
convention. Quick tour::

    from ncnet_tpu import obs

    run = obs.init_run("eval_inloc", obs.default_log_path(out_dir,
                                                          "eval_inloc"),
                       args=args)
    obs.counter("eval_inloc.cache.hits").inc()
    with obs.span("consensus", sync=lambda: corr):
        ...
    run.flush_metrics(phase="matching")
    run.close("ok")

Library code calls ``obs.event``/``obs.span``/``obs.counter``
unconditionally — they no-op (or accumulate invisibly) unless an entry
point opened a run log.
"""

from . import flight, trace
from .events import (
    NULL_RUN,
    RunLog,
    default_log_path,
    event,
    get_run,
    init_run,
    span,
)
from .flight import FlightRecorder
from .heartbeat import Heartbeat, Watchdog
from .trace import SpanCtx, install_compile_telemetry
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    default_registry,
    gauge,
    histogram,
    render_text,
    reset,
    snapshot,
)

__all__ = [
    "NULL_RUN",
    "RunLog",
    "default_log_path",
    "event",
    "get_run",
    "init_run",
    "span",
    "flight",
    "trace",
    "FlightRecorder",
    "SpanCtx",
    "install_compile_telemetry",
    "Heartbeat",
    "Watchdog",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "counter",
    "default_registry",
    "gauge",
    "histogram",
    "render_text",
    "reset",
    "snapshot",
]
