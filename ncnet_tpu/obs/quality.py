"""Online match-quality telemetry: signals, drift detection, quality SLOs.

Everything shipped so far (tracing, SLO burn, cost cards, exemplars)
is systems-level; the match scores themselves — NCNet's whole
confidence signal — were never observed in production. This module
closes that gap on the host side of the serving tail:

* :func:`QualityMonitor.record` books per-request quality signals the
  server already holds (the ``[n, 5]`` match table): mean/max match
  score, the forward↔backward mutual-NN agreement fraction recovered
  from the merged table (``evals/agreement.mutual_nn_fraction``),
  match count, c2f survivor count and the session's ``seed_hit_frac``
  — into labeled histograms per endpoint/mode/rung/tenant.
* :class:`DriftDetector` scores the live score distribution against a
  frozen reference window with PSI (population stability index) over
  the SAME fixed log-bucket ladder every histogram uses
  (``metrics.DEFAULT_BUCKETS``) — bounded state, bucket-aligned with
  every other quality readout. Sustained drift (PSI over threshold for
  ``sustain`` consecutive checks) emits ONE ``quality_drift`` obs
  event and ONE rate-limited ``quality-drift-<endpoint>`` flight dump
  per episode (edge-triggered, plus the flight recorder's per-reason
  cooldown underneath).
* :func:`quality_slos` declares the counter-ratio ``SloSpec`` that
  pages on sustained drift through the EXISTING ``SloEngine`` burn
  machinery — quality pages ride the same multi-window rule, flight
  dumps and ``/healthz`` plumbing as availability pages.

Host-side only, no jax, no device sync: every input is a float or a
numpy array the response path already materialized.
"""

from __future__ import annotations

import bisect
import math
import threading
from collections import deque
from typing import Dict, Optional

from . import flight as _flight
from .events import event
from .metrics import (
    DEFAULT_BUCKETS,
    counter,
    gauge,
    histogram,
    label_key,
    replica_labels,
)
from .slo import SloSpec

#: Observations per drift window (reference and live alike).
DRIFT_WINDOW = 256
#: PSI above this is "shifted" (industry rule of thumb: 0.25 = major).
DRIFT_THRESHOLD = 0.25
#: Consecutive over-threshold checks before an episode starts —
#: one-off spikes (a burst of hard queries) are not drift.
DRIFT_SUSTAIN = 3
#: Observations between PSI evaluations (the check is O(buckets)).
DRIFT_CHECK_EVERY = 32


class DriftDetector:
    """Reference-vs-live PSI over the shared log-bucket sketch.

    The first ``window`` observations freeze the reference sketch; the
    live sketch is a rolling window of the same size. Both are bucket
    count vectors over ``metrics.DEFAULT_BUCKETS`` (+Inf tail), so the
    whole detector is ~70 ints — the same bounded-state bargain the
    histograms make. PSI uses add-half smoothing per bucket so empty
    buckets never produce infinities.

    Not thread-safe on its own; :class:`QualityMonitor` holds the lock.
    """

    def __init__(self, window: int = DRIFT_WINDOW,
                 threshold: float = DRIFT_THRESHOLD,
                 sustain: int = DRIFT_SUSTAIN,
                 check_every: int = DRIFT_CHECK_EVERY,
                 buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        n = len(self.buckets) + 1  # +Inf tail, Prometheus le semantics
        self.window = int(window)
        self.threshold = float(threshold)
        self.sustain = int(sustain)
        self.check_every = int(check_every)
        self._ref = [0] * n
        self._ref_n = 0
        self._live: deque = deque()
        self._live_counts = [0] * n
        self._over = 0
        self._since_check = 0
        self.psi = 0.0
        self.drifting = False

    def offer(self, v: float) -> Optional[str]:
        """One observation; returns ``"start"``/``"end"`` on an episode
        edge, None otherwise."""
        idx = bisect.bisect_left(self.buckets, float(v))
        if self._ref_n < self.window:
            self._ref[idx] += 1
            self._ref_n += 1
            return None
        self._live.append(idx)
        self._live_counts[idx] += 1
        if len(self._live) > self.window:
            self._live_counts[self._live.popleft()] -= 1
        self._since_check += 1
        if len(self._live) < self.window \
                or self._since_check < self.check_every:
            return None
        self._since_check = 0
        self.psi = self._psi()
        self._over = self._over + 1 if self.psi > self.threshold else 0
        was = self.drifting
        self.drifting = self._over >= self.sustain
        if self.drifting and not was:
            return "start"
        if was and not self.drifting:
            return "end"
        return None

    def _psi(self) -> float:
        eps = 0.5
        n = len(self._ref)
        ref_tot = self._ref_n + eps * n
        live_tot = len(self._live) + eps * n
        psi = 0.0
        for r, l in zip(self._ref, self._live_counts):
            p = (r + eps) / ref_tot
            q = (l + eps) / live_tot
            psi += (q - p) * math.log(q / p)
        return psi

    def snapshot(self) -> dict:
        return {
            "psi": round(float(self.psi), 4),
            "drifting": bool(self.drifting),
            "reference_full": self._ref_n >= self.window,
            "live_n": len(self._live),
            "window": self.window,
            "threshold": self.threshold,
        }


class QualityMonitor:
    """Per-request quality signal recorder + per-endpoint drift scoring.

    One process-wide instance (module accessor below, the
    exemplar/flight pattern); servers pass their instance ``labels`` so
    two front doors in one process keep distinct series AND distinct
    drift detectors (keyed by endpoint + labels). Thread-safe — the
    serving handler threads record concurrently.
    """

    def __init__(self, window: int = DRIFT_WINDOW,
                 threshold: float = DRIFT_THRESHOLD,
                 sustain: int = DRIFT_SUSTAIN,
                 check_every: int = DRIFT_CHECK_EVERY):
        self._lock = threading.Lock()
        self._drift_kwargs = dict(window=window, threshold=threshold,
                                  sustain=sustain,
                                  check_every=check_every)
        self._detectors: Dict[tuple, DriftDetector] = {}
        self._episodes = 0

    def clear(self) -> None:
        with self._lock:
            self._detectors.clear()
            self._episodes = 0

    # -- recording --------------------------------------------------------

    def record(self, endpoint: str, rows, *, mode: str = "oneshot",
               rung: int = 0, tenant: Optional[str] = None,
               survivors: Optional[float] = None,
               seed_hit_frac: Optional[float] = None,
               trace_id: Optional[str] = None, labels=None) -> dict:
        """Book one finished request's quality signals.

        ``rows`` is the host match table the response already holds
        (``[n, 5]`` ``(xa, ya, xb, yb, score)``, or None). Returns the
        signals dict — the server attaches it to the response as the
        additive ``quality`` key.
        """
        import numpy as np

        # Deferred: evals pulls jax at package import; the obs package
        # must stay importable without it (tools, offline reports).
        from ncnet_tpu.evals.agreement import mutual_nn_fraction

        rows = (np.asarray(rows, dtype=np.float32) if rows is not None
                else np.zeros((0, 5), np.float32))
        n = int(rows.shape[0])
        score_mean = float(rows[:, 4].mean()) if n else 0.0
        score_max = float(rows[:, 4].max()) if n else 0.0
        mutual = mutual_nn_fraction(rows)
        signals = {
            "n_matches": n,
            "score_mean": round(score_mean, 6),
            "score_max": round(score_max, 6),
            "mutual_frac": round(mutual, 4),
        }
        base = dict(labels) if labels is not None else replica_labels()
        lbls = dict(base)
        lbls.update(endpoint=str(endpoint), mode=str(mode),
                    rung=str(int(rung)))
        if tenant:
            lbls["tenant"] = str(tenant)
        histogram("serving.quality.matches",
                  labels=lbls).observe(n, trace_id=trace_id)
        histogram("serving.quality.score_mean",
                  labels=lbls).observe(score_mean, trace_id=trace_id)
        histogram("serving.quality.score_max",
                  labels=lbls).observe(score_max, trace_id=trace_id)
        histogram("serving.quality.mutual_frac",
                  labels=lbls).observe(mutual, trace_id=trace_id)
        if survivors is not None:
            signals["survivors"] = int(survivors)
        if seed_hit_frac is not None:
            signals["seed_hit_frac"] = round(float(seed_hit_frac), 4)
            histogram("serving.quality.seed_hit_frac",
                      labels=lbls).observe(float(seed_hit_frac),
                                           trace_id=trace_id)
        self._offer_drift(endpoint, score_mean, base, trace_id)
        return signals

    def _offer_drift(self, endpoint, score_mean, labels, trace_id):
        """Feed the endpoint's detector; page counters + episode edges.

        The drift counters deliberately drop the mode/rung/tenant label
        dims: drift is a property of the endpoint's whole score stream
        (a reference frozen per (endpoint, rung, tenant, ...) cell
        would never fill on low-traffic cells).
        """
        base = dict(labels)
        key = (str(endpoint), label_key(labels))
        base["endpoint"] = str(endpoint)
        with self._lock:
            det = self._detectors.get(key)
            if det is None:
                det = DriftDetector(**self._drift_kwargs)
                self._detectors[key] = det
            edge = det.offer(score_mean)
            psi, drifting = det.psi, det.drifting
            if edge == "start":
                self._episodes += 1
        counter("serving.quality.drift_checks", labels=base).inc()
        if not drifting:
            counter("serving.quality.drift_ok", labels=base).inc()
        gauge("serving.quality.drift_psi", labels=base).set(psi)
        if edge == "start":
            counter("serving.quality.drift_episodes", labels=base).inc()
            event("quality_drift", endpoint=str(endpoint), state="start",
                  psi=round(float(psi), 4),
                  threshold=det.threshold, window=det.window,
                  trace_id=trace_id)
            _flight.dump(f"quality-drift-{endpoint}")
        elif edge == "end":
            event("quality_drift", endpoint=str(endpoint), state="end",
                  psi=round(float(psi), 4),
                  threshold=det.threshold, window=det.window,
                  trace_id=trace_id)

    # -- readouts ---------------------------------------------------------

    @property
    def drifting(self) -> bool:
        with self._lock:
            return any(d.drifting for d in self._detectors.values())

    def snapshot(self, labels=None) -> dict:
        """The /healthz ``quality.drift`` block: per-endpoint detector
        state (optionally scoped to one server's label set)."""
        want = label_key(labels) if labels is not None else None
        with self._lock:
            per_endpoint = {
                ep: det.snapshot()
                for (ep, lk), det in sorted(self._detectors.items())
                if want is None or lk == want
            }
            return {
                "drifting": any(d["drifting"]
                                for d in per_endpoint.values()),
                "episodes": self._episodes,
                "per_endpoint": per_endpoint,
            }


#: Process-wide monitor (tests reset via conftest's _reset_obs_metrics,
#: alongside the exemplar reservoir and flight recorder).
_MONITOR = QualityMonitor()


def monitor() -> QualityMonitor:
    return _MONITOR


def quality_slos(
    drift_objective: float = 0.99,
    fast_window_s: float = 300.0,
    slow_window_s: float = 3600.0,
) -> tuple:
    """The quality objectives, shaped for the existing ``SloEngine``.

    ``quality_drift`` is a counter ratio over the drift health counters
    :func:`QualityMonitor.record` books per request: while an endpoint
    drifts, every request is "bad", so the bad fraction saturates at
    1.0 and the burn rate hits 1/(1-objective) = 100x — comfortably
    past both multi-window thresholds. Transient PSI blips never page:
    the detector's ``sustain`` gate runs UNDER this spec, and the
    multi-window burn rule runs on top.
    """
    return (
        SloSpec("quality_drift", drift_objective,
                good="serving.quality.drift_ok",
                total="serving.quality.drift_checks",
                fast_window_s=fast_window_s,
                slow_window_s=slow_window_s),
    )
