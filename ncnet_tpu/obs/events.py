"""Structured JSONL run log for every long-running entry point.

One run = one append-only ``runlog-<run_id>.jsonl`` file. Every line is
one JSON event with the shared envelope::

    {"v": 2, "run_id": ..., "event": <name>,
     "t_wall": <unix seconds>, "t_mono": <monotonic seconds>, ...fields}

Span events may additionally carry ``trace_id``/``span_id``/
``parent_id`` (request-scoped tracing, obs/trace.py — schema v2).

The first event is ``run_start`` (host/pid/git-rev/CLI-args metadata),
the last is ``run_end`` with an exit status — written by an explicit
``close()``, by atexit, or by the chained SIGTERM/SIGINT handler, so a
crashed or preempted run still leaves a final flush on disk (the same
posture as training/checkpoint.py: artifacts must survive a kill at any
point). ``metrics`` events carry `obs.metrics` registry snapshots,
flushed at phase boundaries and at close.

The span form composes with utils/profiling.PhaseTimer's sync
semantics: ``with run.span("consensus", sync=lambda: corr): ...``
blocks on the jax value when the span CLOSES, so device-async dispatch
is not misattributed — but nothing here EVER syncs unless the caller
passes ``sync=`` (ISSUE 1: no new device sync points on the hot path).

Library code logs through the module-level :func:`event` /
:func:`span`, which no-op unless an entry point called
:func:`init_run` — so data/loader.py or localization/driver.py can
instrument unconditionally without coupling unit tests to log files.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import signal
import socket
import sys
import threading
import time
import uuid
from typing import Optional

from . import flight as _flight
from . import metrics as _metrics

#: v2 adds the optional trace envelope fields (trace_id / span_id /
#: parent_id on span events — obs/trace.py) and the `compile` event.
#: v1 files remain readable: every v2 field is additive.
SCHEMA_VERSION = 2

#: Heartbeat/stall events must not count as run progress, or the
#: heartbeat would keep resetting the idle clock it measures.
_NON_PROGRESS_EVENTS = frozenset({"heartbeat", "stall"})


def _git_rev() -> Optional[str]:
    """Current git rev of the repo this module lives in, or None.

    Fenced subprocess: telemetry must never take a run down, and the
    deployment may not even be a git checkout.
    """
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", repo, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def _device_metadata() -> dict:
    """Backend description WITHOUT dialing it.

    jax.devices() can block for minutes on a wedged tunnel
    (utils/profiling.dial_devices exists because of it), so the run log
    only records what is knowable for free: the configured platform and,
    if the caller's backend is already up, its device list is recorded
    later by an explicit `event("devices", ...)` from the entry point.
    """
    return {
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "python": sys.version.split()[0],
    }


class RunLog:
    """Append-only structured JSONL log of one run."""

    def __init__(
        self,
        path: str,
        component: str,
        args=None,
        registry: Optional[_metrics.MetricsRegistry] = None,
        clock=time.monotonic,
        run_id: Optional[str] = None,
        max_bytes: Optional[int] = None,
    ):
        self.path = path
        self.component = component
        # Size-based segment rotation: when the active file crosses
        # max_bytes it is renamed to the next `<stem>.00N<ext>` segment
        # and a fresh base file opened — a serving run can no longer
        # grow one unbounded file. None reads NCNET_RUNLOG_MAX_MB
        # (unset/0 = unbounded). Readers (tools/trace_export.py,
        # tools/obs_report.py, runlog_segments) see the segment set as
        # one log.
        if max_bytes is None:
            try:
                mb = float(os.environ.get("NCNET_RUNLOG_MAX_MB", "0"))
            except ValueError:
                mb = 0.0
            max_bytes = int(mb * 1_000_000) if mb > 0 else 0
        self.max_bytes = int(max_bytes or 0)
        self._segments = 0
        self.run_id = run_id or (
            time.strftime("%Y%m%d-%H%M%S") + "-" + uuid.uuid4().hex[:8]
        )
        self.registry = registry if registry is not None else (
            _metrics.default_registry()
        )
        self.clock = clock
        self._lock = threading.Lock()
        self._closed = False
        self.heartbeat = None  # attached by init_run / the caller
        # Monotonic time of the last NON-heartbeat event: the stall
        # detector's idle clock.
        self.last_progress_mono = clock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")
        self._t0_mono = clock()
        if args is not None and not isinstance(args, dict):
            args = vars(args)  # argparse.Namespace
        self.event(
            "run_start",
            component=component,
            schema=SCHEMA_VERSION,
            git_rev=_git_rev(),
            argv=list(sys.argv),
            args=args,
            **_device_metadata(),
        )

    # -- core API ---------------------------------------------------------

    def event(self, name: str, **fields) -> None:
        """Append one structured event; a closed log drops silently.

        Every write is flushed: events sit at phase boundaries and
        per-step/per-query granularity, so line-flushing is cheap and a
        SIGKILL loses at most the line being written.
        """
        rec = {
            "v": SCHEMA_VERSION,
            "run_id": self.run_id,
            "event": name,
            "t_wall": time.time(),
            "t_mono": self.clock(),
        }
        rec.update(fields)
        # Every event also lands in the bounded in-memory flight
        # recorder (obs/flight.py) — even after close, so a crash
        # during shutdown still has its last events in the ring.
        _flight.record(rec)
        # default=str: a numpy scalar or Path in a field must degrade to
        # text, never take the run down mid-telemetry.
        line = json.dumps(rec, default=str)
        with self._lock:
            if self._closed:
                return
            if name not in _NON_PROGRESS_EVENTS:
                self.last_progress_mono = rec["t_mono"]
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.max_bytes and self._fh.tell() >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Roll the active file out to the next numbered segment and
        reopen the base path fresh. Called with ``self._lock`` held.
        Rotation failures (read-only fs mid-run) degrade to an
        unbounded log rather than taking the run down."""
        try:
            self._fh.close()
            self._segments += 1
            os.replace(self.path, _segment_name(self.path, self._segments))
            self._fh = open(self.path, "a", encoding="utf-8")
        except OSError:
            self.max_bytes = 0
            if self._fh.closed:
                self._fh = open(self.path, "a", encoding="utf-8")

    @contextlib.contextmanager
    def span(self, name: str, sync=None, **fields):
        """Timed block: one ``<name>`` event with ``dur_s`` at close.

        `sync=` follows PhaseTimer.phase: a zero-arg callable (or jax
        value) blocked on when the span closes, so the duration covers
        the device work launched inside the block. Exceptions inside the
        block are re-raised after an event with ``error`` is written.
        """
        t0 = self.clock()
        try:
            yield
        except BaseException as exc:
            self.event(name, kind="span", dur_s=self.clock() - t0,
                       error=f"{type(exc).__name__}: {exc}", **fields)
            raise
        else:
            if sync is not None:
                try:
                    import jax

                    jax.block_until_ready(sync() if callable(sync) else sync)
                except Exception:
                    pass
            self.event(name, kind="span", dur_s=self.clock() - t0, **fields)

    def flush_metrics(self, phase: Optional[str] = None) -> None:
        """Write a ``metrics`` event with the registry's full snapshot."""
        self.event("metrics", phase=phase, snapshot=self.registry.snapshot())

    def close(self, status: str = "ok", **fields) -> None:
        """Final metrics flush + ``run_end`` + file close. Idempotent."""
        with self._lock:
            if self._closed:
                return
        if self.heartbeat is not None:
            try:
                self.heartbeat.stop()
            except Exception:
                pass
        self.flush_metrics(phase="exit")
        self.event("run_end", status=status,
                   dur_s=self.clock() - self._t0_mono, **fields)
        with self._lock:
            self._closed = True
            self._fh.close()
        _deactivate(self)

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close("ok" if exc_type is None
                   else f"error:{exc_type.__name__}")


class _NullRunLog:
    """No-run stand-in so library call sites never need a None check.

    Events are dropped from the (nonexistent) log file but still
    recorded into the flight recorder's in-memory ring — the crash
    triage surface must be live even when no entry point opened a run
    (obs/flight.py).
    """

    run_id = None
    path = None
    heartbeat = None

    def event(self, name: str, **fields) -> None:
        rec = {
            "v": SCHEMA_VERSION,
            "run_id": None,
            "event": name,
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
        }
        rec.update(fields)
        _flight.record(rec)

    @contextlib.contextmanager
    def span(self, name: str, sync=None, **fields):
        yield

    def flush_metrics(self, phase=None) -> None:
        pass

    def close(self, status: str = "ok", **fields) -> None:
        pass


NULL_RUN = _NullRunLog()

_active_lock = threading.Lock()
_active: list = []  # innermost-last stack of open RunLogs
_exit_hooks_installed = False
_hooks_lock = threading.Lock()


def _deactivate(run: RunLog) -> None:
    with _active_lock:
        if run in _active:
            _active.remove(run)


def _close_all(status: str) -> None:
    with _active_lock:
        runs = list(_active)
    for run in runs:
        try:
            run.close(status)
        except Exception:
            pass


def _install_exit_hooks() -> None:
    """atexit + chained SIGTERM/SIGINT final flush, installed once.

    The signal handlers CHAIN: after closing the run logs they re-invoke
    whatever handler was installed before (or re-raise the default
    behavior), so a preemption SIGTERM still terminates and an operator
    ^C still interrupts. SIGALRM is deliberately untouched —
    utils/profiling.run_with_alarm owns it.
    """
    global _exit_hooks_installed
    with _hooks_lock:
        if _exit_hooks_installed:
            return
        _exit_hooks_installed = True
    atexit.register(_close_all, "atexit")
    # Unhandled exceptions (main thread or any worker) dump the flight
    # recorder's ring before the traceback prints — the last N events
    # of a crash that never reached a clean close.
    _flight.install_excepthooks()

    def _chain(signum, prev):
        def handler(sig, frame):
            _close_all(f"signal:{signal.Signals(sig).name}")
            if callable(prev):
                prev(sig, frame)
            else:
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
                signal.raise_signal(sig)
        return handler

    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            prev = signal.getsignal(signum)
            signal.signal(signum, _chain(signum, prev))
        except (ValueError, OSError):
            # Non-main thread or embedded interpreter: atexit still
            # covers the clean paths; don't fight the host process.
            pass


def init_run(
    component: str,
    path: str,
    args=None,
    heartbeat_s: Optional[float] = None,
    registry: Optional[_metrics.MetricsRegistry] = None,
) -> RunLog:
    """Open a run log, make it the current run, start its heartbeat.

    `heartbeat_s` <= 0 disables the heartbeat thread; None reads
    ``NCNET_OBS_HEARTBEAT_S`` (default 30). The first beat is emitted
    immediately, so even a seconds-long smoke run records >= 1
    heartbeat event (the acceptance contract for CPU-smoke runs).
    """
    run = RunLog(path, component, args=args, registry=registry)
    with _active_lock:
        _active.append(run)
    _install_exit_hooks()
    # Identity as a metric (Prometheus info idiom): version/backend/
    # replica ride the labels of a constant-1 gauge, so a scraper knows
    # who it is talking to without parsing /healthz.
    try:
        _metrics.set_build_info(registry=run.registry, component=component)
    except Exception:
        pass
    # Compile telemetry rides every run: recompile storms are a serving
    # problem first, but an eval that silently retraces per query is
    # the same disease (obs/trace.install_compile_telemetry).
    from .trace import install_compile_telemetry

    install_compile_telemetry()
    if heartbeat_s is None:
        try:
            heartbeat_s = float(os.environ.get("NCNET_OBS_HEARTBEAT_S", "30"))
        except ValueError:
            heartbeat_s = 30.0
    if heartbeat_s > 0:
        from .heartbeat import Heartbeat

        run.heartbeat = Heartbeat(run, interval_s=heartbeat_s)
        run.heartbeat.start()
    return run


def get_run():
    """The innermost active RunLog, or the shared no-op."""
    with _active_lock:
        return _active[-1] if _active else NULL_RUN


def event(name: str, **fields) -> None:
    """Log to the current run (no-op when no run is active)."""
    get_run().event(name, **fields)


def span(name: str, sync=None, **fields):
    return get_run().span(name, sync=sync, **fields)


def _segment_name(path: str, n: int) -> str:
    """``runlog-x.jsonl`` + 3 -> ``runlog-x.003.jsonl``."""
    stem, ext = os.path.splitext(path)
    return f"{stem}.{n:03d}{ext}"


def runlog_segments(path: str) -> list:
    """All on-disk segments of a (possibly rotated) run log, oldest
    first, the active base file last. An unrotated log returns
    ``[path]`` — readers can always iterate the result and see one
    chronological record stream."""
    stem, ext = os.path.splitext(path)
    directory = os.path.dirname(path) or "."
    prefix = os.path.basename(stem) + "."
    segments = []
    try:
        names = os.listdir(directory)
    except OSError:
        names = []
    for n in names:
        if not (n.startswith(prefix) and n.endswith(ext)):
            continue
        mid = n[len(prefix):len(n) - len(ext)] if ext else n[len(prefix):]
        if len(mid) == 3 and mid.isdigit():
            segments.append(os.path.join(directory, n))
    segments.sort()
    if os.path.exists(path) or not segments:
        segments.append(path)
    return segments


def default_log_path(directory: str, component: str) -> str:
    """Canonical run-log location: ``<dir>/runlog-<component>-<stamp>.jsonl``.

    One file per run (never reused): --resume reruns of the eval CLI
    append new FILES next to the old ones instead of interleaving run
    records, and tools/obs_report.py consumes exactly one run per file.
    """
    stamp = time.strftime("%Y%m%d-%H%M%S") + "-" + uuid.uuid4().hex[:6]
    return os.path.join(directory, f"runlog-{component}-{stamp}.jsonl")
