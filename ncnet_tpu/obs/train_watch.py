"""Training observatory: per-step telemetry, divergence sentinel, beacons.

Every observability layer so far (labeled metrics, tracing, SLO burn,
PSI drift, flight recorder) points at the serving stack; the trainer
emitted one build event and two gauges. This module gives a training
run the same instrument panel a request gets:

* :meth:`TrainWatch.steps` + :meth:`TrainWatch.book` split each step's
  wall time into ``data_wait`` / ``forward_backward`` / ``update``
  children under a ``train.step`` trace root and book the
  ``train.step_time_s`` / ``train.data_wait_s`` / ``train.device_s``
  histograms, so ``tools/trace_export.py`` / ``tools/obs_report.py``
  render a training run exactly like a serving request. The phases are
  host-attributed: under async dispatch the device work hides inside
  ``forward_backward`` (the dispatch-to-dispatch window) via
  backpressure; ``update`` is the host-side bookkeeping residue.

* a **bounded-lag divergence sentinel**: loss / grad-norm leave the
  step as device scalars and are resolved to host floats only once
  they are ``lag`` steps old — by then the device has finished them,
  so the fetch is never a same-step sync. A non-finite value, or a
  sustained grad-norm PSI drift (the :class:`~.quality.DriftDetector`
  ladder over the shared log buckets), emits ONE ``train_divergence``
  event + ONE rate-limited ``train-divergence`` flight dump per
  episode, carrying the last-K resolved-step ring with each step's
  batch manifest ids — then applies the declared policy
  (``halt`` raises :class:`TrainDivergence`, ``skip`` lets the caller
  drop the offending step from the curve, ``dump-only`` records).
  The resolved loss passes through the ``train.step`` failpoint's
  ``corrupt`` mode (docs/RELIABILITY.md), so chaos runs can flip
  exactly one loss to NaN without touching the real parameters.

* **per-host step beacons**: every booked step publishes a
  ``train.step_index`` gauge labeled with this host's replica id;
  :func:`publish_host_lag` folds a fleet view merged by
  ``obs/aggregate.py`` into per-host ``train.host_behind_steps``
  gauges, so a straggling host is visible in ``tools/fleet_status.py``
  before elastic multi-host training (ROADMAP item 4) makes it fatal.

* **checkpoint health**: :func:`book_checkpoint_save` /
  :func:`book_checkpoint_load` record save/load duration, on-disk
  bytes and the completed-checkpoint chain depth of the run dir.

Host-side only, no jax import: device scalars are resolved through
``np.asarray`` (the ``__array__`` protocol), exactly like
``obs/quality.py``. A :class:`~.heartbeat.Watchdog` can be armed
around each step (``step_timeout_s``) so a hung device step hard-exits
with a flight dump instead of wedging silently; the run-level
:class:`~.heartbeat.Heartbeat` started by ``obs.init_run`` covers the
softer stall case (idle runlog -> ``stall`` event + dump).

All TrainWatch state is owned by the single training thread; the only
other thread it touches is the Watchdog's, which never reads it.
"""

from __future__ import annotations

import math
import os
import time
from collections import deque
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Tuple,
)

import numpy as np

from . import flight as _flight
from . import trace
from .events import event
from .heartbeat import Watchdog
from .metrics import MetricsRegistry, default_registry, replica_id
from .quality import DriftDetector

#: Steps a loss/grad-norm device scalar ages before the sentinel
#: resolves it to a host float. By then the device has long finished
#: the value, so the fetch never blocks dispatch (the "bounded lag").
SENTINEL_LAG = 2

#: Resolved-step ring carried by a ``train-divergence`` flight dump —
#: the steps (with batch manifest ids) leading into the divergence.
RING_SIZE = 32

POLICIES = ("halt", "skip", "dump-only")


class TrainDivergence(RuntimeError):
    """Raised by the ``halt`` divergence policy: training observed a
    non-finite loss/grad-norm (or sustained grad-norm drift) and was
    told not to continue. The run log closes ``error:TrainDivergence``
    and the ``train-divergence`` flight dump has already been written
    by the time this propagates."""

    def __init__(self, kind: str, epoch: int, step: int):
        super().__init__(
            f"training diverged ({kind}) at epoch {epoch} step {step}; "
            "see the train-divergence flight dump"
        )
        self.kind = kind
        self.epoch = epoch
        self.step = step


class TrainWatch:
    """Per-step training telemetry + divergence sentinel + step beacon.

    Single-threaded by design: one instance lives inside one training
    loop and every method is called from that loop's thread (the race
    lint's shared-state inventory stays empty). Typical wiring::

        watch = TrainWatch(policy=args.on_divergence, lr=args.lr,
                           log_interval=args.log_interval)
        for i, batch in watch.steps(device_prefetch(src, put), start=s):
            failpoints.fire("train.step", payload=i)
            trainable, opt_state, loss, aux = train_step(...)
            watch.book(epoch=epoch, step=i, loss=loss,
                       grad_norm=aux["grad_norm"],
                       update_ratio=aux["update_ratio"],
                       batch_ids=batch.get("_indices"))
        watch.drain()   # resolve the tail before averaging the epoch
    """

    def __init__(
        self,
        policy: str = "halt",
        lag: int = SENTINEL_LAG,
        ring_size: int = RING_SIZE,
        log_interval: int = 1,
        lr: Optional[float] = None,
        host: Optional[str] = None,
        step_timeout_s: float = 0.0,
        registry: Optional[MetricsRegistry] = None,
        drift: Optional[DriftDetector] = None,
        clock: Callable[[], float] = time.monotonic,
        flight_dir: Optional[str] = None,
        watchdog: Optional[Watchdog] = None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"bad divergence policy {policy!r} (want one of {POLICIES})"
            )
        self.policy = policy
        self.lag = max(int(lag), 0)
        self.log_interval = max(int(log_interval), 1)
        self.lr = lr
        self.step_index = -1
        self._registry = registry if registry is not None \
            else default_registry()
        self._drift = drift if drift is not None else DriftDetector()
        self._clock = clock
        self._flight_dir = flight_dir
        self._host = host or replica_id() or "host0"
        self._pending: deque = deque()
        self._ring: deque = deque(maxlen=max(int(ring_size), 1))
        self._divergent: List[Tuple[int, int]] = []
        self._in_divergence = False
        self._t_boundary: Optional[float] = None
        self._t_batch_ready: Optional[float] = None
        self._data_wait_s = 0.0
        self._step_timeout_s = float(step_timeout_s)
        self._watchdog = watchdog
        if watchdog is None and self._step_timeout_s > 0:
            self._watchdog = Watchdog(label="train-step").start()

    # -- step loop --------------------------------------------------------

    def reset_epoch(self) -> None:
        """Drop the step-boundary timestamp at an epoch edge so the
        first step of the next epoch does not absorb validation /
        checkpoint wall time into its ``update`` residue."""
        self._t_boundary = None
        self._t_batch_ready = None
        self._data_wait_s = 0.0

    def steps(self, iterable: Iterable,
              start: int = 0) -> Iterator[Tuple[int, Any]]:
        """Yield ``(step, batch)`` while timing each batch wait.

        The wait on ``next()`` is the input pipeline's share of the
        step (``data_wait``); the watchdog (when armed) gets a fresh
        deadline per batch so a hung device step — not a long epoch —
        trips it.
        """
        it = iter(iterable)
        i = start
        while True:
            t0 = self._clock()
            try:
                batch = next(it)
            except StopIteration:
                if self._watchdog is not None:
                    self._watchdog.disarm()
                return
            self._t_batch_ready = self._clock()
            self._data_wait_s = self._t_batch_ready - t0
            if self._t_boundary is None:
                self._t_boundary = t0
            if self._watchdog is not None and self._step_timeout_s > 0:
                self._watchdog.arm(self._step_timeout_s)
            yield i, batch
            i += 1

    def book(
        self,
        *,
        epoch: int,
        step: int,
        loss: Any = None,
        grad_norm: Any = None,
        update_ratio: Any = None,
        batch_ids: Any = None,
    ) -> None:
        """Book one completed step (called right after dispatch returns).

        ``loss`` / ``grad_norm`` / ``update_ratio`` stay device scalars
        here — they enter the sentinel queue and are resolved ``lag``
        steps later. ``batch_ids`` is the batch's manifest-index array
        (host-side), carried into the divergence ring.
        """
        now = self._clock()
        if self._watchdog is not None:
            self._watchdog.disarm()
        ready = self._t_batch_ready if self._t_batch_ready is not None \
            else now
        wait_s = max(self._data_wait_s, 0.0)
        fb_s = max(now - ready, 0.0)
        total = wait_s + fb_s
        if self._t_boundary is not None:
            total = max(now - self._t_boundary, total)
        upd_s = max(total - wait_s - fb_s, 0.0)
        self._t_boundary = now
        self._t_batch_ready = None
        self._data_wait_s = 0.0

        reg = self._registry
        reg.histogram("train.step_time_s").observe(total)
        reg.histogram("train.data_wait_s").observe(wait_s)
        reg.histogram("train.device_s").observe(fb_s)
        reg.counter("train.steps").inc()
        if self.lr is not None:
            reg.gauge("train.lr").set(float(self.lr))

        # Span tree: root written after its children (readers build the
        # tree from ids, not file order) — one request-shaped record
        # per step for trace_export/obs_report.
        root = trace.new_root()
        trace.emit_span("data_wait", wait_s, parents=[root])
        trace.emit_span("forward_backward", fb_s, parents=[root])
        trace.emit_span("update", upd_s, parents=[root])
        trace.emit_root(root, "train.step", total, step=step, epoch=epoch)

        self.publish_beacon(step)

        ids = None
        if batch_ids is not None:
            try:
                ids = [int(x) for x in np.asarray(batch_ids).reshape(-1)]
            except (TypeError, ValueError):
                ids = None
        self._pending.append({
            "epoch": int(epoch), "step": int(step), "loss": loss,
            "grad_norm": grad_norm, "update_ratio": update_ratio,
            "batch_ids": ids,
        })
        while len(self._pending) > self.lag:
            self._resolve(self._pending.popleft())

    def publish_beacon(self, step: int) -> None:
        """Publish this host's step position as a replica-labeled gauge
        (merged fleet-side by ``obs/aggregate.py`` ->
        :func:`publish_host_lag`)."""
        self.step_index = int(step)
        self._registry.gauge(
            "train.step_index", labels={"replica": self._host}
        ).set(float(step))

    # -- sentinel ---------------------------------------------------------

    def drain(self) -> None:
        """Resolve every queued step (epoch end / shutdown): the tail
        of the run must not escape the sentinel just because no younger
        step aged it out."""
        while self._pending:
            self._resolve(self._pending.popleft())

    def close(self) -> None:
        self.drain()
        if self._watchdog is not None:
            self._watchdog.stop()

    @property
    def divergent_steps(self) -> List[Tuple[int, int]]:
        """``(epoch, step)`` of every step the sentinel flagged."""
        return list(self._divergent)

    def _resolve(self, rec: Dict[str, Any]) -> None:
        # Late import: reliability.failpoints imports the obs package;
        # a module-level import here would cycle through obs/__init__.
        from ..reliability import failpoints

        loss_f = gn_f = ur_f = None
        if rec["loss"] is not None:
            arr = np.asarray(rec["loss"], dtype=np.float32).reshape(-1)
            # The chaos plant: an armed ``train.step=corrupt`` site
            # NaN-poisons this resolved COPY — telemetry sees the
            # divergence, the real parameters are untouched.
            arr = failpoints.corrupt("train.step", arr)
            loss_f = float(arr[0]) if arr.size else None
        if rec["grad_norm"] is not None:
            gn_f = float(
                np.asarray(rec["grad_norm"], dtype=np.float32).reshape(-1)[0]
            )
        if rec["update_ratio"] is not None:
            ur_f = float(
                np.asarray(
                    rec["update_ratio"], dtype=np.float32
                ).reshape(-1)[0]
            )

        finite = True
        reg = self._registry
        if loss_f is not None:
            if math.isfinite(loss_f):
                reg.gauge("train.loss").set(loss_f)
            else:
                finite = False
        if gn_f is not None:
            if math.isfinite(gn_f):
                reg.gauge("train.grad_norm").set(gn_f)
            else:
                finite = False
        if ur_f is not None and math.isfinite(ur_f):
            reg.gauge("train.update_ratio").set(ur_f)

        epoch, step = rec["epoch"], rec["step"]
        entry = {
            "epoch": epoch,
            "step": step,
            "loss": loss_f if loss_f is not None and math.isfinite(loss_f)
            else None,
            "grad_norm": gn_f if gn_f is not None and math.isfinite(gn_f)
            else None,
            "batch_ids": rec["batch_ids"],
        }
        if not finite:
            entry["nonfinite"] = True
        self._ring.append(entry)

        if step % self.log_interval == 0 or not finite:
            fields = {"epoch": epoch, "step": step, "loss": entry["loss"],
                      "grad_norm": entry["grad_norm"]}
            if ur_f is not None and math.isfinite(ur_f):
                fields["update_ratio"] = ur_f
            if not finite:
                fields["nonfinite"] = True
            event("train_step", **fields)

        kind = None
        if not finite:
            kind = "nonfinite"
        elif gn_f is not None:
            edge = self._drift.offer(gn_f)
            reg.gauge("train.grad_norm_psi").set(float(self._drift.psi))
            if edge == "start":
                kind = "grad_norm_drift"
        if kind is None:
            # A finite step re-arms the episode edge: a later relapse
            # gets its own event + dump.
            self._in_divergence = False
            return
        self._divergence(kind, entry)

    def _divergence(self, kind: str, entry: Dict[str, Any]) -> None:
        epoch, step = entry["epoch"], entry["step"]
        self._divergent.append((epoch, step))
        self._registry.counter("train.divergence.events").inc()
        if not self._in_divergence:
            self._in_divergence = True
            # Event first: it lands in the flight ring, so the dump
            # written next carries the verdict AND the last-K steps
            # (with batch manifest ids) that led into it.
            event("train_divergence", kind=kind, epoch=epoch, step=step,
                  policy=self.policy, batch_ids=entry.get("batch_ids"),
                  psi=round(float(self._drift.psi), 4),
                  ring=list(self._ring))
            try:
                _flight.dump("train-divergence", directory=self._flight_dir)
            except Exception:
                pass
        if self.policy == "halt":
            raise TrainDivergence(kind, epoch, step)


# -- fleet-side beacon merge ----------------------------------------------


def publish_host_lag(view: dict,
                     registry: Optional[MetricsRegistry] = None
                     ) -> Dict[str, float]:
    """Per-host behind-steps from a merged fleet view.

    ``view`` is ``aggregate.merge_snapshots`` output (registry
    snapshots or ``fleet_view`` scrapes — the scraped gauge name has
    dots sanitized to underscores, both spellings are accepted). The
    lead host defines the front; every host's lag is published as a
    replica-labeled ``train.host_behind_steps`` gauge and returned.
    """
    gauges = view.get("gauges") or {}
    entry = gauges.get("train.step_index") \
        or gauges.get("train_step_index")
    per = (entry or {}).get("per_replica") or {}
    if not per:
        return {}
    lead = max(per.values())
    behind = {rid: float(lead - v) for rid, v in sorted(per.items())}
    reg = registry if registry is not None else default_registry()
    for rid, lag in behind.items():
        reg.gauge(
            "train.host_behind_steps", labels={"replica": rid}
        ).set(lag)
    return behind


# -- elastic membership ---------------------------------------------------


def book_membership(generation: int, hosts_live: int,
                    registry: Optional[MetricsRegistry] = None) -> None:
    """Publish the elastic-training membership view: the current
    generation and the live host count (training/elastic.py books this
    at start and after every adopted generation change)."""
    reg = registry if registry is not None else default_registry()
    reg.gauge("train.generation").set(float(generation))
    reg.gauge("train.hosts_live").set(float(hosts_live))


def book_resume(generation: int, lost_steps: int,
                registry: Optional[MetricsRegistry] = None) -> None:
    """Record one survivor resume: the resume count and the re-trained
    ("lost") steps between the detected position and the checkpoint
    position training restarted from."""
    del generation  # gauge side is book_membership's; kept for symmetry
    reg = registry if registry is not None else default_registry()
    reg.counter("train.resumes").inc()
    if lost_steps > 0:
        reg.counter("train.lost_steps").inc(float(lost_steps))


# -- checkpoint health ----------------------------------------------------


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for fn in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                pass
    return total


def chain_depth(root: str) -> int:
    """COMPLETE checkpoint dirs (meta.json present — the completeness
    marker resolve_resume_dir keys on) under a run directory."""
    try:
        entries = os.listdir(root)
    except OSError:
        return 0
    return sum(
        1 for e in entries
        if os.path.isfile(os.path.join(root, e, "meta.json"))
    )


def book_checkpoint_save(path: str, root: str, dur_s: float) -> None:
    """Record one checkpoint save: duration, bytes on disk, and the
    run dir's completed-checkpoint chain depth."""
    reg = default_registry()
    reg.histogram("train.ckpt.save_s").observe(float(dur_s))
    reg.gauge("train.ckpt.bytes").set(float(_dir_bytes(path)))
    reg.gauge("train.ckpt.chain_depth").set(float(chain_depth(root)))


def book_checkpoint_load(path: str, dur_s: float) -> None:
    """Record one checkpoint load's duration."""
    del path  # symmetry with book_checkpoint_save; labels may ride later
    default_registry().histogram("train.ckpt.load_s").observe(float(dur_s))
