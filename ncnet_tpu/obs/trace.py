"""Request-scoped tracing: trace/span IDs over the obs run log.

PR 1's ``RunLog.span`` records flat timed blocks — fine for a
single-threaded eval loop, blind once the serving path (PR 2) moves one
request across thread boundaries: HTTP handler thread (admit) →
batcher bookkeeping (queue wait) → worker thread (batch assembly,
device dispatch) → handler thread again (respond). This module adds
the structure those flat spans lack:

* every span event carries ``trace_id`` / ``span_id`` / ``parent_id``
  in the ordinary run-log envelope (schema v2, docs/OBSERVABILITY.md),
  so one request's wall time decomposes into a tree that
  ``tools/obs_report.py`` renders and ``tools/trace_export.py`` turns
  into a Perfetto view;
* propagation is ``contextvars``-based within a thread and **explicit**
  across threads: :func:`current` captures the active context (e.g. at
  ``DeadlineBatcher.submit``), :func:`attach` re-establishes it on the
  worker thread, and :func:`emit_span` books externally-measured
  durations (queue wait) into the right tree without a context switch;
* one *batched* piece of work serves many requests: :func:`span` and
  :func:`emit_span` fan out — under an :func:`attach` of several
  requests' contexts they emit one span event **per requesting trace**,
  so a batch's device time shows up in every rider's tree (with
  ``batch_size`` telling the reader it was shared).

Spans opened with no active trace degrade to the flat PR-1 form (a
``kind: "span"`` event with no IDs) — library code instruments
unconditionally, exactly like ``obs.event``.

Cross-process propagation (docs/OBSERVABILITY.md, "Cross-process
tracing"): :func:`inject` serializes a context into the
``X-NCNet-Trace: <trace_id>-<span_id>-<flags>`` header and
:func:`extract` parses it back on the far side; ``trace(parent=...)``
then CONTINUES the caller's trace (same ``trace_id``, ``parent_id``
pointing at the remote span, ``remote_parent: true`` on the root
record) instead of rooting a new one, so ``tools/trace_export.py`` can
join a client runlog and N replica runlogs into one tree. Head
sampling rides the header's flags byte: :func:`set_sample_rate` sets
the local root-sampling probability, the decision propagates with the
context, and unsampled traces write no span events — except error
paths (exceptions, and anything a handler marks via :func:`force`),
which are always recorded locally. ``trace.sampled`` /
``trace.dropped`` count root decisions; ``trace.remote_spans`` counts
roots continued from a remote parent.

Also here: :func:`install_compile_telemetry` hooks ``jax.monitoring``
duration listeners so every XLA backend compile lands in the run log as
a ``compile`` event and in the ``jit.compile_time_s`` histogram — the
recompile-storm signal for serving (an unwarmed bucket shape recompiles
on the hot path; the histogram's count is the storm detector).
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import threading
import time
import uuid
from typing import Iterable, NamedTuple, Optional, Tuple

#: Wire header carrying trace context across processes
#: (docs/SERVING.md): ``X-NCNet-Trace: <trace_id>-<span_id>-<flags>``,
#: ids lowercase hex, flags a two-digit hex byte (bit 0 = sampled).
TRACE_HEADER = "X-NCNet-Trace"

FLAG_SAMPLED = 0x1

_HEX = frozenset("0123456789abcdef")


class SpanCtx(NamedTuple):
    """One active span: everything a child needs to parent onto it.

    ``sampled`` is the propagated head-sampling decision (made once at
    the root, inherited by every child and across the wire);
    ``remote`` marks a context that arrived via :func:`extract` — its
    span lives in another process's runlog.
    """

    trace_id: str
    span_id: str
    sampled: bool = True
    remote: bool = False


#: Active span contexts for this thread/task. A tuple because one unit
#: of work can serve several traces at once (a shared batch); () means
#: no trace is active.
_CTX: "contextvars.ContextVar[Tuple[SpanCtx, ...]]" = contextvars.ContextVar(
    "ncnet_obs_trace_ctx", default=()
)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


# -- head sampling --------------------------------------------------------

# guarded-by: atomic -- float publish; a racing reader roots at the old rate
_sample_rate = 1.0

_forced_lock = threading.Lock()
# guarded-by: _forced_lock
_forced: dict = {}  # trace_id -> extra fields for the (late) root record
_FORCED_MAX = 1024


def set_sample_rate(rate: float) -> float:
    """Set the local head-sampling probability for NEW roots (clamped
    to [0, 1]); remote-continued traces keep the caller's decision.
    Error paths are recorded regardless. Returns the clamped rate."""
    global _sample_rate
    rate = min(1.0, max(0.0, float(rate)))
    _sample_rate = rate
    from . import metrics

    metrics.gauge("trace.sample_rate").set(rate)
    return rate


def sample_rate() -> float:
    return _sample_rate


def _decide() -> bool:
    rate = _sample_rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def force(ctx: SpanCtx, **fields) -> None:
    """Record this trace's root span even if unsampled.

    For error/breaker/poison response paths: the handler discovers the
    outcome AFTER children were (correctly) suppressed, but the root —
    with whatever ``fields`` are passed here — must still land locally
    so a failing unsampled request is never invisible. Bounded map;
    consumed at root emission."""
    with _forced_lock:
        if len(_forced) >= _FORCED_MAX:
            _forced.pop(next(iter(_forced)))
        prev = _forced.setdefault(ctx.trace_id, {})
        prev.update(fields)


def _take_forced(trace_id: str) -> Optional[dict]:
    with _forced_lock:
        return _forced.pop(trace_id, None)


# -- wire propagation -----------------------------------------------------


def inject(ctx: Optional[SpanCtx] = None) -> Optional[str]:
    """Serialize ``ctx`` (default: the first active context) into the
    ``X-NCNet-Trace`` header value, or None with no active trace."""
    if ctx is None:
        cur = current()
        ctx = cur[0] if cur else None
    if ctx is None:
        return None
    flags = FLAG_SAMPLED if ctx.sampled else 0
    return f"{ctx.trace_id}-{ctx.span_id}-{flags:02x}"


def extract(value) -> Optional[SpanCtx]:
    """Parse an ``X-NCNet-Trace`` header value into a remote
    :class:`SpanCtx`; malformed or absent values return None (the
    server then roots a fresh trace — propagation is best-effort,
    never a 400)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 3:
        return None
    trace_id, span_id, flags = parts
    if not trace_id or not span_id:
        return None
    if not (set(trace_id) <= _HEX and set(span_id) <= _HEX):
        return None
    try:
        bits = int(flags, 16)
    except ValueError:
        return None
    return SpanCtx(trace_id, span_id, bool(bits & FLAG_SAMPLED), True)


def new_root(parent: Optional[SpanCtx] = None) -> SpanCtx:
    """Mint a context WITHOUT opening a ``with`` block — for
    state-machine lifecycles (a client request crossing a retry loop,
    a bulk flight bouncing through an event loop) whose root span
    closes far from where it opens. ``parent`` (local or extracted)
    continues its trace and inherits its sampled flag; None roots a
    new trace under the head-sampling decision. Close it with
    :func:`emit_root`."""
    if parent is not None:
        return SpanCtx(parent.trace_id, _new_id(), parent.sampled)
    return SpanCtx(_new_id(), _new_id(), _decide())


def child_of(ctx: SpanCtx) -> SpanCtx:
    """A fresh child context under ``ctx`` (same trace, new span id)."""
    return SpanCtx(ctx.trace_id, _new_id(), ctx.sampled)


def emit_root(ctx: SpanCtx, name: str, dur_s: float,
              parent: Optional[SpanCtx] = None, **fields) -> None:
    """Write the span record for a :func:`new_root`-minted context.
    Suppressed for unsampled traces unless the fields carry ``error``
    or the trace was :func:`force`-marked."""
    extra = _take_forced(ctx.trace_id)
    if not (ctx.sampled or "error" in fields or extra is not None):
        return
    if extra:
        fields = {**fields, **extra}
    if not ctx.sampled:
        fields.setdefault("sampled", False)
    _emit(name, kind="span", dur_s=dur_s, trace_id=ctx.trace_id,
          span_id=ctx.span_id,
          parent_id=parent.span_id if parent is not None else None,
          **fields)


def current() -> Tuple[SpanCtx, ...]:
    """The active span context(s); capture at a thread boundary and
    re-establish on the far side with :func:`attach`."""
    return _CTX.get()


@contextlib.contextmanager
def attach(contexts: Iterable[SpanCtx]):
    """Make ``contexts`` the active span context(s) for the block —
    the cross-thread half of propagation (the batcher worker attaches
    the union of its batch's request contexts before running the
    engine, so engine spans land in every rider's tree)."""
    token = _CTX.set(tuple(contexts))
    try:
        yield
    finally:
        _CTX.reset(token)


def _emit(name: str, **fields) -> None:
    # Late import: events imports metrics; trace must stay leaf-ish to
    # avoid an import cycle with events' flight wiring.
    from . import events

    events.event(name, **fields)


def emit_span(
    name: str,
    dur_s: float,
    parents: Optional[Iterable[SpanCtx]] = None,
    **fields,
) -> None:
    """Book one already-measured span into the tree(s).

    For durations measured outside any single thread's control flow —
    the batcher's queue wait is ``t_run - t_submit`` across two threads
    and cannot be a ``with`` block anywhere. ``parents=None`` uses the
    ambient context; an empty parent set degrades to a flat span event.
    """
    parents = current() if parents is None else tuple(parents)
    if not parents:
        _emit(name, kind="span", dur_s=dur_s, **fields)
        return
    for p in parents:
        if not (p.sampled or "error" in fields):
            continue  # head sampling: unsampled trees write no spans
        _emit(
            name,
            kind="span",
            dur_s=dur_s,
            trace_id=p.trace_id,
            span_id=_new_id(),
            parent_id=p.span_id,
            **fields,
        )


@contextlib.contextmanager
def span(name: str, sync=None, **fields):
    """Timed block as a child of the active context(s).

    Under a multi-context :func:`attach` (a shared batch) one event is
    emitted per requesting trace — same duration, distinct
    ``span_id``s. With no active trace this is exactly the flat
    ``obs.span`` form. ``sync=`` follows PhaseTimer/RunLog.span: a
    zero-arg callable (or jax value) blocked on at close, so device
    work launched inside the block is attributed to it — never passed
    on hot paths (ISSUE 1: no new device sync points).
    """
    parents = current()
    if not parents:
        from . import events

        with events.span(name, sync=sync, **fields):
            yield ()
        return
    children = tuple(child_of(p) for p in parents)
    token = _CTX.set(children)
    t0 = time.monotonic()
    try:
        yield children
    except BaseException as exc:
        dur = time.monotonic() - t0
        _CTX.reset(token)
        token = None
        # Error spans are always recorded, sampled or not — a failing
        # unsampled request must still leave a local trail.
        for p, c in zip(parents, children):
            _emit(name, kind="span", dur_s=dur, trace_id=c.trace_id,
                  span_id=c.span_id, parent_id=p.span_id,
                  error=f"{type(exc).__name__}: {exc}", **fields)
        raise
    else:
        if sync is not None:
            try:
                import jax

                jax.block_until_ready(sync() if callable(sync) else sync)
            except Exception:
                pass
        dur = time.monotonic() - t0
        for p, c in zip(parents, children):
            if not p.sampled:
                continue
            _emit(name, kind="span", dur_s=dur, trace_id=c.trace_id,
                  span_id=c.span_id, parent_id=p.span_id, **fields)
    finally:
        if token is not None:
            _CTX.reset(token)


@contextlib.contextmanager
def trace(name: str, parent: Optional[SpanCtx] = None,
          kind: Optional[str] = None, **fields):
    """Root span of a trace (one serving request, one eval query).

    Yields the root :class:`SpanCtx`; everything opened inside — in
    this thread, or on another thread via :func:`current`/
    :func:`attach` — parents onto it. The root event is written at
    close (after its children; readers build the tree from IDs, not
    file order).

    ``parent=None`` roots a NEW trace (``parent_id: None``) under the
    local head-sampling decision. ``parent`` set — typically an
    :func:`extract`-ed wire context — CONTINUES the caller's trace:
    same ``trace_id``, ``parent_id`` pointing at the remote span,
    inherited sampled flag, and ``remote_parent: true`` on the record
    when the parent crossed a process boundary. ``kind`` labels the
    span's role (``client``/``server``/``internal``) as ``span_kind``
    on the record.
    """
    from . import metrics

    if parent is not None:
        root = SpanCtx(parent.trace_id, _new_id(), parent.sampled)
        parent_id: Optional[str] = parent.span_id
    else:
        root = SpanCtx(_new_id(), _new_id(), _decide())
        parent_id = None
    metrics.counter(
        "trace.sampled" if root.sampled else "trace.dropped").inc()
    if parent is not None and parent.remote:
        metrics.counter("trace.remote_spans").inc()
        fields.setdefault("remote_parent", True)
    if kind is not None:
        fields.setdefault("span_kind", kind)
    token = _CTX.set((root,))
    t0 = time.monotonic()
    try:
        yield root
    except BaseException as exc:
        extra = _take_forced(root.trace_id) or {}
        if not root.sampled:
            extra.setdefault("sampled", False)
        _emit(name, kind="span", dur_s=time.monotonic() - t0,
              trace_id=root.trace_id, span_id=root.span_id,
              parent_id=parent_id,
              error=f"{type(exc).__name__}: {exc}", **{**fields, **extra})
        raise
    else:
        extra = _take_forced(root.trace_id)
        if root.sampled or extra is not None:
            merged = {**fields, **(extra or {})}
            if not root.sampled:
                merged.setdefault("sampled", False)
            _emit(name, kind="span", dur_s=time.monotonic() - t0,
                  trace_id=root.trace_id, span_id=root.span_id,
                  parent_id=parent_id, **merged)
    finally:
        _CTX.reset(token)


# -- jax.monitoring compile telemetry -------------------------------------

_compile_telemetry_installed = False
_install_lock = threading.Lock()


def install_compile_telemetry() -> bool:
    """Register a ``jax.monitoring`` duration listener once (process
    lifetime — jax keeps listeners global, so this is deliberately not
    un-installable); returns whether the hook is live.

    Every ``/jax/core/compile/backend_compile_duration`` event becomes
    a run-log ``compile`` event plus an observation on the
    ``jit.compile_time_s`` histogram (and a ``jit.compiles`` counter) —
    with the PR's bucketed histograms, ``/metrics`` then exposes a
    compile-time distribution a recompile storm visibly shifts. Other
    ``/jax/core/compile/*`` stage durations (jaxpr trace, MLIR
    lowering) are folded into ``jit.compile_time_s``-adjacent
    histograms under their stage name but do not emit events — they
    fire on cache hits too and would drown the signal.

    Called from ``obs.init_run`` and the serving entry point; safe (and
    a no-op) without jax installed, so the obs layer keeps working in
    stubbed-out environments.
    """
    global _compile_telemetry_installed
    with _install_lock:
        if _compile_telemetry_installed:
            return True
        try:
            from jax import monitoring as _monitoring
        except Exception:
            return False
        _compile_telemetry_installed = True

    def _listener(jax_event: str, duration: float, **kwargs) -> None:
        try:
            if "compile" not in jax_event:
                return
            from . import metrics

            stage = jax_event.rstrip("/").rsplit("/", 1)[-1]
            if stage == "backend_compile_duration":
                metrics.counter("jit.compiles").inc()
                metrics.histogram("jit.compile_time_s").observe(duration)
                _emit("compile", jax_event=jax_event, dur_s=duration,
                      **{k: str(v) for k, v in kwargs.items()})
            else:
                metrics.histogram(
                    "jit." + stage.replace("_duration", "") + "_s"
                ).observe(duration)
        except Exception:
            # A telemetry listener inside jit tracing must never take
            # the compile down.
            pass

    _monitoring.register_event_duration_secs_listener(_listener)
    return True
