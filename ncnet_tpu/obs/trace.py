"""Request-scoped tracing: trace/span IDs over the obs run log.

PR 1's ``RunLog.span`` records flat timed blocks — fine for a
single-threaded eval loop, blind once the serving path (PR 2) moves one
request across thread boundaries: HTTP handler thread (admit) →
batcher bookkeeping (queue wait) → worker thread (batch assembly,
device dispatch) → handler thread again (respond). This module adds
the structure those flat spans lack:

* every span event carries ``trace_id`` / ``span_id`` / ``parent_id``
  in the ordinary run-log envelope (schema v2, docs/OBSERVABILITY.md),
  so one request's wall time decomposes into a tree that
  ``tools/obs_report.py`` renders and ``tools/trace_export.py`` turns
  into a Perfetto view;
* propagation is ``contextvars``-based within a thread and **explicit**
  across threads: :func:`current` captures the active context (e.g. at
  ``DeadlineBatcher.submit``), :func:`attach` re-establishes it on the
  worker thread, and :func:`emit_span` books externally-measured
  durations (queue wait) into the right tree without a context switch;
* one *batched* piece of work serves many requests: :func:`span` and
  :func:`emit_span` fan out — under an :func:`attach` of several
  requests' contexts they emit one span event **per requesting trace**,
  so a batch's device time shows up in every rider's tree (with
  ``batch_size`` telling the reader it was shared).

Spans opened with no active trace degrade to the flat PR-1 form (a
``kind: "span"`` event with no IDs) — library code instruments
unconditionally, exactly like ``obs.event``.

Also here: :func:`install_compile_telemetry` hooks ``jax.monitoring``
duration listeners so every XLA backend compile lands in the run log as
a ``compile`` event and in the ``jit.compile_time_s`` histogram — the
recompile-storm signal for serving (an unwarmed bucket shape recompiles
on the hot path; the histogram's count is the storm detector).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from typing import Iterable, NamedTuple, Optional, Tuple


class SpanCtx(NamedTuple):
    """One active span: everything a child needs to parent onto it."""

    trace_id: str
    span_id: str


#: Active span contexts for this thread/task. A tuple because one unit
#: of work can serve several traces at once (a shared batch); () means
#: no trace is active.
_CTX: "contextvars.ContextVar[Tuple[SpanCtx, ...]]" = contextvars.ContextVar(
    "ncnet_obs_trace_ctx", default=()
)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def current() -> Tuple[SpanCtx, ...]:
    """The active span context(s); capture at a thread boundary and
    re-establish on the far side with :func:`attach`."""
    return _CTX.get()


@contextlib.contextmanager
def attach(contexts: Iterable[SpanCtx]):
    """Make ``contexts`` the active span context(s) for the block —
    the cross-thread half of propagation (the batcher worker attaches
    the union of its batch's request contexts before running the
    engine, so engine spans land in every rider's tree)."""
    token = _CTX.set(tuple(contexts))
    try:
        yield
    finally:
        _CTX.reset(token)


def _emit(name: str, **fields) -> None:
    # Late import: events imports metrics; trace must stay leaf-ish to
    # avoid an import cycle with events' flight wiring.
    from . import events

    events.event(name, **fields)


def emit_span(
    name: str,
    dur_s: float,
    parents: Optional[Iterable[SpanCtx]] = None,
    **fields,
) -> None:
    """Book one already-measured span into the tree(s).

    For durations measured outside any single thread's control flow —
    the batcher's queue wait is ``t_run - t_submit`` across two threads
    and cannot be a ``with`` block anywhere. ``parents=None`` uses the
    ambient context; an empty parent set degrades to a flat span event.
    """
    parents = current() if parents is None else tuple(parents)
    if not parents:
        _emit(name, kind="span", dur_s=dur_s, **fields)
        return
    for p in parents:
        _emit(
            name,
            kind="span",
            dur_s=dur_s,
            trace_id=p.trace_id,
            span_id=_new_id(),
            parent_id=p.span_id,
            **fields,
        )


@contextlib.contextmanager
def span(name: str, sync=None, **fields):
    """Timed block as a child of the active context(s).

    Under a multi-context :func:`attach` (a shared batch) one event is
    emitted per requesting trace — same duration, distinct
    ``span_id``s. With no active trace this is exactly the flat
    ``obs.span`` form. ``sync=`` follows PhaseTimer/RunLog.span: a
    zero-arg callable (or jax value) blocked on at close, so device
    work launched inside the block is attributed to it — never passed
    on hot paths (ISSUE 1: no new device sync points).
    """
    parents = current()
    if not parents:
        from . import events

        with events.span(name, sync=sync, **fields):
            yield ()
        return
    children = tuple(SpanCtx(p.trace_id, _new_id()) for p in parents)
    token = _CTX.set(children)
    t0 = time.monotonic()
    try:
        yield children
    except BaseException as exc:
        dur = time.monotonic() - t0
        _CTX.reset(token)
        token = None
        for p, c in zip(parents, children):
            _emit(name, kind="span", dur_s=dur, trace_id=c.trace_id,
                  span_id=c.span_id, parent_id=p.span_id,
                  error=f"{type(exc).__name__}: {exc}", **fields)
        raise
    else:
        if sync is not None:
            try:
                import jax

                jax.block_until_ready(sync() if callable(sync) else sync)
            except Exception:
                pass
        dur = time.monotonic() - t0
        for p, c in zip(parents, children):
            _emit(name, kind="span", dur_s=dur, trace_id=c.trace_id,
                  span_id=c.span_id, parent_id=p.span_id, **fields)
    finally:
        if token is not None:
            _CTX.reset(token)


@contextlib.contextmanager
def trace(name: str, **fields):
    """Root span of a NEW trace (one serving request, one eval query).

    Yields the root :class:`SpanCtx`; everything opened inside — in
    this thread, or on another thread via :func:`current`/
    :func:`attach` — parents onto it. The root event is written at
    close (after its children; readers build the tree from IDs, not
    file order) with ``parent_id: None`` marking it a root.
    """
    root = SpanCtx(_new_id(), _new_id())
    token = _CTX.set((root,))
    t0 = time.monotonic()
    try:
        yield root
    except BaseException as exc:
        _emit(name, kind="span", dur_s=time.monotonic() - t0,
              trace_id=root.trace_id, span_id=root.span_id, parent_id=None,
              error=f"{type(exc).__name__}: {exc}", **fields)
        raise
    else:
        _emit(name, kind="span", dur_s=time.monotonic() - t0,
              trace_id=root.trace_id, span_id=root.span_id, parent_id=None,
              **fields)
    finally:
        _CTX.reset(token)


# -- jax.monitoring compile telemetry -------------------------------------

_compile_telemetry_installed = False
_install_lock = threading.Lock()


def install_compile_telemetry() -> bool:
    """Register a ``jax.monitoring`` duration listener once (process
    lifetime — jax keeps listeners global, so this is deliberately not
    un-installable); returns whether the hook is live.

    Every ``/jax/core/compile/backend_compile_duration`` event becomes
    a run-log ``compile`` event plus an observation on the
    ``jit.compile_time_s`` histogram (and a ``jit.compiles`` counter) —
    with the PR's bucketed histograms, ``/metrics`` then exposes a
    compile-time distribution a recompile storm visibly shifts. Other
    ``/jax/core/compile/*`` stage durations (jaxpr trace, MLIR
    lowering) are folded into ``jit.compile_time_s``-adjacent
    histograms under their stage name but do not emit events — they
    fire on cache hits too and would drown the signal.

    Called from ``obs.init_run`` and the serving entry point; safe (and
    a no-op) without jax installed, so the obs layer keeps working in
    stubbed-out environments.
    """
    global _compile_telemetry_installed
    with _install_lock:
        if _compile_telemetry_installed:
            return True
        try:
            from jax import monitoring as _monitoring
        except Exception:
            return False
        _compile_telemetry_installed = True

    def _listener(jax_event: str, duration: float, **kwargs) -> None:
        try:
            if "compile" not in jax_event:
                return
            from . import metrics

            stage = jax_event.rstrip("/").rsplit("/", 1)[-1]
            if stage == "backend_compile_duration":
                metrics.counter("jit.compiles").inc()
                metrics.histogram("jit.compile_time_s").observe(duration)
                _emit("compile", jax_event=jax_event, dur_s=duration,
                      **{k: str(v) for k, v in kwargs.items()})
            else:
                metrics.histogram(
                    "jit." + stage.replace("_duration", "") + "_s"
                ).observe(duration)
        except Exception:
            # A telemetry listener inside jit tracing must never take
            # the compile down.
            pass

    _monitoring.register_event_duration_secs_listener(_listener)
    return True
