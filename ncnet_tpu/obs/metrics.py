"""Thread-safe run-metrics registry: Counter / Gauge / Histogram.

The long-running entry points accumulate host-side counters (cache
hits, padding waste, prefetch starvation, retry records) that used to
live in scattered instance attributes and die with the process. This
registry is the ONE place they accumulate; `snapshot()` serializes the
whole registry into a plain dict that `obs.events.RunLog` flushes into
the run log at phase boundaries and at exit.

Design constraints (ISSUE 1 tentpole):
  * host-side only — nothing here touches jax or forces a device sync;
    callers record values they already hold on the host (a float() the
    training loop was doing anyway, a queue depth, a stack size);
  * thread-safe — the eval CLI records from its decode-prefetch pool
    threads while the main thread dispatches, and the data loader
    records from its producer thread;
  * cheap — inc/set/observe are a lock acquire + a few float ops, so
    they can sit on per-step/per-query paths without moving benchmarks.

Metric naming convention (docs/OBSERVABILITY.md): dotted lowercase
``component.subsystem.name`` with the unit as a suffix where ambiguous
(``_s``, ``_bytes``, ``_frac``) — e.g. ``train.step_time_s``,
``eval_inloc.cache.hits``, ``data.loader.starved``.
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, Optional

#: Fixed log-spaced histogram buckets: 4 per decade over 1e-4 .. 1e4
#: (upper bounds, Prometheus ``le`` semantics; everything above the
#: last bound lands in +Inf). One shared ladder for every histogram —
#: seconds (queue wait 1e-3..1e1, compile times 1e-2..1e3) and small
#: counts (batch sizes 1..16) all resolve to distinct buckets, and a
#: fixed ladder keeps A/B diffs bucket-aligned across runs. 33 bounds
#: = 34 ints per histogram: bounded state, unlike a sample list.
DEFAULT_BUCKETS = tuple(10.0 ** (k / 4.0) for k in range(-16, 17))


class Counter:
    """Monotonically increasing count (events, items, bytes)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Gauge:
    """Last-written value (queue depth, hit rate, pairs/s)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Histogram:
    """Bucketed summary of an observed distribution (step times, sizes).

    Keeps count/sum/min/max/last plus fixed log-spaced bucket counts
    (:data:`DEFAULT_BUCKETS`), so p50/p95/p99 exist (bucket-edge
    interpolation, clamped to the observed min/max) and ``/metrics``
    can expose cumulative ``_bucket`` lines — all in bounded state (a
    training run observes one value per step; an unbounded sample list
    would grow with the run).
    """

    def __init__(self, name: str, lock: threading.Lock,
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self._lock = lock
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # last: +Inf
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.last: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        # Prometheus `le`: the first bucket whose upper bound is >= v.
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v
            self._bucket_counts[idx] += 1

    def _quantile_locked(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile; caller holds the lock."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        for i, c in enumerate(self._bucket_counts):
            if not c:
                continue
            cum += c
            if cum >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (self.buckets[i] if i < len(self.buckets)
                      else (self.max if self.max is not None else lo))
                frac = (target - (cum - c)) / c
                est = lo + (hi - lo) * frac
                # The ladder is coarser than the data near the edges:
                # never report outside the observed range.
                if self.min is not None:
                    est = max(est, self.min)
                if self.max is not None:
                    est = min(est, self.max)
                return est
        return self.max

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            return self._quantile_locked(q)

    def bucket_counts(self):
        """(upper_bounds, cumulative_counts) aligned lists; the final
        entry is the +Inf bucket (== count)."""
        with self._lock:
            cum, out = 0, []
            for c in self._bucket_counts:
                cum += c
                out.append(cum)
            return self.buckets, out

    def snapshot(self) -> dict:
        with self._lock:
            mean = self.sum / self.count if self.count else None
            return {
                "count": self.count,
                "sum": self.sum,
                "mean": mean,
                "min": self.min,
                "max": self.max,
                "last": self.last,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors.

    One process-wide default registry (module functions below) so
    library code (data/loader.py, localization/driver.py) can record
    without plumbing a registry handle through every call chain; tests
    construct private registries or `reset()` the default.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                # Each metric gets its own lock: a hot counter on the
                # loader's producer thread must not contend with the
                # registry-structure lock held during snapshot().
                m = cls(name, threading.Lock())
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def snapshot(self) -> dict:
        """Serialize every metric into a plain-JSON dict, grouped by kind."""
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(items):
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def render_text(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the registry.

        The serving front end's ``GET /metrics`` serves this; any
        Prometheus-compatible scraper consumes it directly. Mapping:

          * dotted metric names sanitize to underscores
            (``serving.queue_wait_s`` -> ``serving_queue_wait_s``);
          * Counter -> ``<name>_total`` counter;
          * Gauge   -> gauge (unset gauges are omitted — Prometheus has
            no null and 0.0 would be a lie);
          * Histogram -> a Prometheus histogram: cumulative
            ``<name>_bucket{le="..."}`` lines over the fixed log-spaced
            ladder (DEFAULT_BUCKETS; empty leading/trailing buckets are
            elided, the cumulative contract is preserved by always
            emitting ``+Inf``), ``_sum``/``_count``, plus
            ``<name>_min``/``<name>_max``/``<name>_last`` gauges.
        """
        with self._lock:
            items = sorted(self._metrics.items())
        lines = []

        def emit(name, kind, value):
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {float(value):g}")

        for name, m in items:
            pname = _prom_name(name)
            if isinstance(m, Counter):
                emit(f"{pname}_total", "counter", m.snapshot())
            elif isinstance(m, Gauge):
                v = m.snapshot()
                if v is not None:
                    emit(pname, "gauge", v)
            else:
                s = m.snapshot()
                bounds, cum = m.bucket_counts()
                lines.append(f"# TYPE {pname} histogram")
                # Elide the empty head (cum 0) and the saturated tail
                # (every bound past the max is a repeat of count) —
                # the ladder spans 8 decades and most metrics live in
                # 2; scrape size should track the data, not the ladder.
                prev = 0
                for b, c in zip(bounds, cum):
                    if c == 0 or (c == prev and c == s["count"]):
                        prev = c
                        continue
                    prev = c
                    lines.append(f'{pname}_bucket{{le="{b:g}"}} {c:g}')
                lines.append(
                    f'{pname}_bucket{{le="+Inf"}} {float(s["count"]):g}'
                )
                lines.append(f"{pname}_sum {float(s['sum']):g}")
                lines.append(f"{pname}_count {float(s['count']):g}")
                for field in ("min", "max", "last"):
                    if s[field] is not None:
                        emit(f"{pname}_{field}", "gauge", s[field])
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


_PROM_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Sanitize a dotted metric name into a valid Prometheus name."""
    name = _PROM_INVALID.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT


def counter(name: str) -> Counter:
    return _DEFAULT.counter(name)


def gauge(name: str) -> Gauge:
    return _DEFAULT.gauge(name)


def histogram(name: str) -> Histogram:
    return _DEFAULT.histogram(name)


def snapshot() -> dict:
    return _DEFAULT.snapshot()


def render_text() -> str:
    return _DEFAULT.render_text()


def reset() -> None:
    _DEFAULT.reset()
