"""Thread-safe run-metrics registry: Counter / Gauge / Histogram.

The long-running entry points accumulate host-side counters (cache
hits, padding waste, prefetch starvation, retry records) that used to
live in scattered instance attributes and die with the process. This
registry is the ONE place they accumulate; `snapshot()` serializes the
whole registry into a plain dict that `obs.events.RunLog` flushes into
the run log at phase boundaries and at exit.

Design constraints (ISSUE 1 tentpole):
  * host-side only — nothing here touches jax or forces a device sync;
    callers record values they already hold on the host (a float() the
    training loop was doing anyway, a queue depth, a stack size);
  * thread-safe — the eval CLI records from its decode-prefetch pool
    threads while the main thread dispatches, and the data loader
    records from its producer thread;
  * cheap — inc/set/observe are a lock acquire + a few float ops, so
    they can sit on per-step/per-query paths without moving benchmarks.

Metric naming convention (docs/OBSERVABILITY.md): dotted lowercase
``component.subsystem.name`` with the unit as a suffix where ambiguous
(``_s``, ``_bytes``, ``_frac``) — e.g. ``train.step_time_s``,
``eval_inloc.cache.hits``, ``data.loader.starved``.

Labels (ISSUE 6 tentpole): every accessor takes an optional label set
(``counter("serving.requests", labels={"replica": "r0"})``). A metric
name now addresses a *family*; each distinct label set is its own child
series with its own lock and state. Unlabeled access is the child with
the empty label set, so pre-label callers and snapshot consumers see
byte-identical behavior. Labeled series appear in ``snapshot()`` under
``name{k="v",...}`` keys (sorted keys — see :func:`format_series`) and
in ``render_text()`` as standard Prometheus label blocks.
"""

from __future__ import annotations

import bisect
import os
import re
import threading
import time
from typing import Dict, Iterable, Mapping, Optional, Tuple, Union

#: Fixed log-spaced histogram buckets: 4 per decade over 1e-4 .. 1e4
#: (upper bounds, Prometheus ``le`` semantics; everything above the
#: last bound lands in +Inf). One shared ladder for every histogram —
#: seconds (queue wait 1e-3..1e1, compile times 1e-2..1e3) and small
#: counts (batch sizes 1..16) all resolve to distinct buckets, and a
#: fixed ladder keeps A/B diffs bucket-aligned across runs. 33 bounds
#: = 34 ints per histogram: bounded state, unlike a sample list.
DEFAULT_BUCKETS = tuple(10.0 ** (k / 4.0) for k in range(-16, 17))

#: A normalized label set: sorted ``(key, value)`` pairs. The empty
#: tuple is the unlabeled series.
LabelKey = Tuple[Tuple[str, str], ...]

Labels = Union[None, Mapping[str, object], Iterable[Tuple[str, object]]]


def label_key(labels: Labels) -> LabelKey:
    """Normalize a label mapping into the canonical sorted-tuple key."""
    if not labels:
        return ()
    items = labels.items() if isinstance(labels, Mapping) else labels
    return tuple(sorted((_prom_name(str(k)), str(v)) for k, v in items))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape_label_value(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            n = v[i + 1]
            out.append({"n": "\n"}.get(n, n))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _render_labels(labels: LabelKey) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + body + "}"


def format_series(name: str, labels: Labels = None) -> str:
    """Canonical series key: ``name`` or ``name{k="v",...}`` (sorted keys).

    Shared by ``snapshot()``, ``obs/aggregate.py`` and
    ``tools/obs_report.py`` so every layer agrees on series identity.
    """
    return name + _render_labels(label_key(labels))


_SERIES_RE = re.compile(r"^(?P<name>[^{]+?)(?:\{(?P<labels>.*)\})?$")
_LABEL_RE = re.compile(r'([A-Za-z_:][A-Za-z0-9_:.]*)="((?:[^"\\]|\\.)*)"')


def parse_series(series: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`format_series`: ``name{k="v"}`` -> (name, labels)."""
    m = _SERIES_RE.match(series)
    if not m:
        return series, {}
    labels = {}
    if m.group("labels"):
        for k, v in _LABEL_RE.findall(m.group("labels")):
            labels[k] = _unescape_label_value(v)
    return m.group("name"), labels


def bucket_quantile(bounds, bucket_counts, count, q,
                    lo_clamp=None, hi_clamp=None) -> Optional[float]:
    """Bucket-interpolated quantile over per-bucket (delta) counts.

    ``bucket_counts`` has ``len(bounds) + 1`` entries, the last being
    the +Inf bucket. Shared by :class:`Histogram` and the fleet-level
    merge in ``obs/aggregate.py`` so a merged histogram quantiles
    exactly like a local one.
    """
    if not count:
        return None
    target = q * count
    cum = 0
    for i, c in enumerate(bucket_counts):
        if not c:
            continue
        cum += c
        if cum >= target:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = (bounds[i] if i < len(bounds)
                  else (hi_clamp if hi_clamp is not None else lo))
            frac = (target - (cum - c)) / c
            est = lo + (hi - lo) * frac
            # The ladder is coarser than the data near the edges:
            # never report outside the observed range.
            if lo_clamp is not None:
                est = max(est, lo_clamp)
            if hi_clamp is not None:
                est = min(est, hi_clamp)
            return est
    return hi_clamp


class Counter:
    """Monotonically increasing count (events, items, bytes)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.labels: LabelKey = ()
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Gauge:
    """Last-written value (queue depth, hit rate, pairs/s)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.labels: LabelKey = ()
        self._lock = lock
        self._value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Histogram:
    """Bucketed summary of an observed distribution (step times, sizes).

    Keeps count/sum/min/max/last plus fixed log-spaced bucket counts
    (:data:`DEFAULT_BUCKETS`), so p50/p95/p99 exist (bucket-edge
    interpolation, clamped to the observed min/max) and ``/metrics``
    can expose cumulative ``_bucket`` lines — all in bounded state (a
    training run observes one value per step; an unbounded sample list
    would grow with the run).
    """

    def __init__(self, name: str, lock: threading.Lock,
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self.labels: LabelKey = ()
        self._lock = lock
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # last: +Inf
        # Per-bucket exemplar: idx -> (trace_id, value, t_wall). Bounded
        # by construction (one slot per bucket, last observation wins)
        # and only populated when a caller attaches a trace_id.
        self._exemplars: Dict[int, tuple] = {}
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.last: Optional[float] = None

    def observe(self, v: float, trace_id: Optional[str] = None,
                sampled: bool = True) -> None:
        v = float(v)
        # Prometheus `le`: the first bucket whose upper bound is >= v.
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v
            self._bucket_counts[idx] += 1
            # ``sampled=False`` (head-sampled-out trace, obs/trace.py)
            # still counts the observation but skips the exemplar: a
            # trace_id with no spans behind it is a dead link.
            if trace_id is not None and sampled:
                self._exemplars[idx] = (str(trace_id), v, time.time())

    def exemplars(self) -> Dict[int, tuple]:
        """Bucket-index -> (trace_id, value, t_wall) exemplar map (the
        index aligns with ``buckets``; len(buckets) is +Inf)."""
        with self._lock:
            return dict(self._exemplars)

    def _quantile_locked(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile; caller holds the lock."""
        return bucket_quantile(self.buckets, self._bucket_counts,
                               self.count, q,
                               lo_clamp=self.min, hi_clamp=self.max)

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            return self._quantile_locked(q)

    def bucket_counts(self):
        """(upper_bounds, cumulative_counts) aligned lists; the final
        entry is the +Inf bucket (== count)."""
        with self._lock:
            cum, out = 0, []
            for c in self._bucket_counts:
                cum += c
                out.append(cum)
            return self.buckets, out

    def snapshot(self) -> dict:
        with self._lock:
            mean = self.sum / self.count if self.count else None
            # Sparse cumulative bucket list: only the finite bounds
            # whose bucket is non-empty ([le, cumulative] pairs; the
            # +Inf remainder is implied by `count`). This is what lets
            # obs/aggregate.py merge replicas' histograms exactly.
            buckets, cum = [], 0
            for b, c in zip(self.buckets, self._bucket_counts):
                cum += c
                if c:
                    buckets.append([b, cum])
            return {
                "count": self.count,
                "sum": self.sum,
                "mean": mean,
                "min": self.min,
                "max": self.max,
                "last": self.last,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
                "buckets": buckets,
            }


class _Family:
    """One metric name -> its children, keyed by normalized label set."""

    __slots__ = ("name", "cls", "children")

    def __init__(self, name: str, cls):
        self.name = name
        self.cls = cls
        self.children: Dict[LabelKey, object] = {}


class MetricsRegistry:
    """Name -> metric-family map with get-or-create accessors.

    One process-wide default registry (module functions below) so
    library code (data/loader.py, localization/driver.py) can record
    without plumbing a registry handle through every call chain; tests
    construct private registries or `reset()` the default.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get_or_create(self, name: str, cls, labels: Labels = None):
        key = label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, cls)
                self._families[name] = fam
            elif fam.cls is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{fam.cls.__name__}, requested {cls.__name__}"
                )
            child = fam.children.get(key)
            if child is None:
                # Each child gets its own lock: a hot counter on the
                # loader's producer thread must not contend with the
                # registry-structure lock held during snapshot().
                child = cls(name, threading.Lock())
                child.labels = key
                fam.children[key] = child
            return child

    def counter(self, name: str, labels: Labels = None) -> Counter:
        return self._get_or_create(name, Counter, labels)

    def gauge(self, name: str, labels: Labels = None) -> Gauge:
        return self._get_or_create(name, Gauge, labels)

    def histogram(self, name: str, labels: Labels = None) -> Histogram:
        return self._get_or_create(name, Histogram, labels)

    def _sorted_families(self):
        with self._lock:
            fams = sorted(self._families.items())
            return [(name, fam.cls,
                     [fam.children[k] for k in sorted(fam.children)])
                    for name, fam in fams]

    def snapshot(self) -> dict:
        """Serialize every series into a plain-JSON dict, grouped by kind.

        Unlabeled series keep their bare name as the key (pre-label
        files stay readable by the same tools); labeled series key as
        ``name{k="v",...}`` via :func:`format_series`.
        """
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, cls, children in self._sorted_families():
            kind = ("counters" if cls is Counter
                    else "gauges" if cls is Gauge else "histograms")
            for ch in children:
                out[kind][name + _render_labels(ch.labels)] = ch.snapshot()
        return out

    def render_text(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the registry.

        The serving front end's ``GET /metrics`` serves this; any
        Prometheus-compatible scraper consumes it directly. Mapping:

          * dotted metric names sanitize to underscores
            (``serving.queue_wait_s`` -> ``serving_queue_wait_s``);
          * labeled children render as standard ``{k="v"}`` blocks,
            one ``# TYPE`` line per family;
          * Counter -> ``<name>_total`` counter;
          * Gauge   -> gauge (unset gauges are omitted — Prometheus has
            no null and 0.0 would be a lie);
          * Histogram -> a Prometheus histogram: cumulative
            ``<name>_bucket{le="..."}`` lines over the fixed log-spaced
            ladder (DEFAULT_BUCKETS; empty leading/trailing buckets are
            elided, the cumulative contract is preserved by always
            emitting ``+Inf``), ``_sum``/``_count``, plus
            ``<name>_min``/``<name>_max``/``<name>_last`` gauges.
            Buckets that carry an exemplar (an ``observe`` with a
            ``trace_id`` — serving's latency histograms) get the
            OpenMetrics exemplar suffix
            `` # {trace_id="..."} <value> <timestamp>`` appended, so a
            scrape links a tail bucket straight to a request trace.
        """
        lines = []
        for name, cls, children in self._sorted_families():
            pname = _prom_name(name)
            if cls is Counter:
                lines.append(f"# TYPE {pname}_total counter")
                for ch in children:
                    lines.append(
                        f"{pname}_total{_render_labels(ch.labels)}"
                        f" {float(ch.snapshot()):g}"
                    )
            elif cls is Gauge:
                rows = [(ch.labels, ch.snapshot()) for ch in children]
                rows = [(l, v) for l, v in rows if v is not None]
                if rows:
                    lines.append(f"# TYPE {pname} gauge")
                    for l, v in rows:
                        lines.append(
                            f"{pname}{_render_labels(l)} {float(v):g}")
            else:
                lines.append(f"# TYPE {pname} histogram")
                aux = {"min": [], "max": [], "last": []}
                for ch in children:
                    s = ch.snapshot()
                    bounds, cum = ch.bucket_counts()
                    exemplars = ch.exemplars()
                    # Elide the empty head (cum 0) and the saturated
                    # tail (every bound past the max repeats count) —
                    # the ladder spans 8 decades and most metrics live
                    # in 2; scrape size should track the data, not the
                    # ladder.
                    prev = 0
                    for i, (b, c) in enumerate(zip(bounds, cum)):
                        if c == 0 or (c == prev and c == s["count"]):
                            prev = c
                            continue
                        prev = c
                        lbls = ch.labels + (("le", f"{b:g}"),)
                        lines.append(
                            f"{pname}_bucket{_render_labels(lbls)} {c:g}"
                            + _render_exemplar(exemplars.get(i))
                        )
                    lbls = ch.labels + (("le", "+Inf"),)
                    lines.append(
                        f"{pname}_bucket{_render_labels(lbls)}"
                        f" {float(s['count']):g}"
                        + _render_exemplar(exemplars.get(len(bounds)))
                    )
                    lines.append(
                        f"{pname}_sum{_render_labels(ch.labels)}"
                        f" {float(s['sum']):g}"
                    )
                    lines.append(
                        f"{pname}_count{_render_labels(ch.labels)}"
                        f" {float(s['count']):g}"
                    )
                    for field in aux:
                        if s[field] is not None:
                            aux[field].append((ch.labels, s[field]))
                for field, rows in aux.items():
                    if rows:
                        lines.append(f"# TYPE {pname}_{field} gauge")
                        for l, v in rows:
                            lines.append(
                                f"{pname}_{field}{_render_labels(l)}"
                                f" {float(v):g}"
                            )
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._families.clear()


_PROM_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Sanitize a dotted metric name into a valid Prometheus name."""
    name = _PROM_INVALID.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _render_exemplar(ex) -> str:
    """OpenMetrics exemplar suffix for one ``_bucket`` line.

    ``ex``: (trace_id, value, t_wall) from ``Histogram.exemplars``, or
    None (empty suffix). The trace_id is sanitized to the exemplar
    label charset (aggregate's parser strips the whole suffix either
    way — see ``_parse_sample``)."""
    if not ex:
        return ""
    trace_id, value, t_wall = ex
    tid = re.sub(r'[\\"\n]', "", str(trace_id))
    return f' # {{trace_id="{tid}"}} {float(value):g} {t_wall:.3f}'


_DEFAULT = MetricsRegistry()

# --- replica identity -------------------------------------------------
#
# A process serving as part of a fleet labels its hot-path series with
# `replica="<id>"` so obs/aggregate.py can merge N scrapes without
# double counting. Identity resolution: explicit set_replica_id() (the
# serving CLI's --replica_id) > NCNET_REPLICA_ID env > unlabeled.
# Objects that need per-instance identity in ONE process (two
# MatchServers in a test) pass explicit labels instead.

_replica_lock = threading.Lock()
_replica_id: Optional[str] = None


def set_replica_id(rid: Optional[str]) -> None:
    global _replica_id
    with _replica_lock:
        _replica_id = str(rid) if rid else None


def replica_id() -> Optional[str]:
    with _replica_lock:
        if _replica_id is not None:
            return _replica_id
    return os.environ.get("NCNET_REPLICA_ID") or None


def replica_labels() -> Dict[str, str]:
    """`{"replica": id}` when an identity is configured, else `{}`."""
    rid = replica_id()
    return {"replica": rid} if rid else {}


def set_build_info(registry: Optional[MetricsRegistry] = None,
                   **extra: object) -> Gauge:
    """Register the `ncnet.build_info` identity gauge (value always 1).

    Prometheus "info metric" idiom: identity rides the labels (version,
    backend, replica id), the value is constant — scrapers see who a
    replica is without parsing /healthz.
    """
    from ncnet_tpu import __version__

    info = {"version": __version__,
            "backend": os.environ.get("JAX_PLATFORMS") or "default"}
    rid = replica_id()
    if rid:
        info["replica"] = rid
    for k, v in extra.items():
        if v:
            info[k] = str(v)
    g = (registry or _DEFAULT).gauge("ncnet.build_info", labels=info)
    g.set(1.0)
    return g


def default_registry() -> MetricsRegistry:
    return _DEFAULT


def counter(name: str, labels: Labels = None) -> Counter:
    return _DEFAULT.counter(name, labels)


def gauge(name: str, labels: Labels = None) -> Gauge:
    return _DEFAULT.gauge(name, labels)


def histogram(name: str, labels: Labels = None) -> Histogram:
    return _DEFAULT.histogram(name, labels)


def snapshot() -> dict:
    return _DEFAULT.snapshot()


def render_text() -> str:
    return _DEFAULT.render_text()


def reset() -> None:
    _DEFAULT.reset()
