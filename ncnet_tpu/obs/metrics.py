"""Thread-safe run-metrics registry: Counter / Gauge / Histogram.

The long-running entry points accumulate host-side counters (cache
hits, padding waste, prefetch starvation, retry records) that used to
live in scattered instance attributes and die with the process. This
registry is the ONE place they accumulate; `snapshot()` serializes the
whole registry into a plain dict that `obs.events.RunLog` flushes into
the run log at phase boundaries and at exit.

Design constraints (ISSUE 1 tentpole):
  * host-side only — nothing here touches jax or forces a device sync;
    callers record values they already hold on the host (a float() the
    training loop was doing anyway, a queue depth, a stack size);
  * thread-safe — the eval CLI records from its decode-prefetch pool
    threads while the main thread dispatches, and the data loader
    records from its producer thread;
  * cheap — inc/set/observe are a lock acquire + a few float ops, so
    they can sit on per-step/per-query paths without moving benchmarks.

Metric naming convention (docs/OBSERVABILITY.md): dotted lowercase
``component.subsystem.name`` with the unit as a suffix where ambiguous
(``_s``, ``_bytes``, ``_frac``) — e.g. ``train.step_time_s``,
``eval_inloc.cache.hits``, ``data.loader.starved``.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional


class Counter:
    """Monotonically increasing count (events, items, bytes)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Gauge:
    """Last-written value (queue depth, hit rate, pairs/s)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Histogram:
    """Streaming summary of an observed distribution (step times, sizes).

    Keeps count/sum/min/max/last — enough for the report tool's mean and
    range without storing samples (a training run observes one value per
    step; an unbounded sample list would grow with the run).
    """

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.last: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v

    def snapshot(self) -> dict:
        with self._lock:
            mean = self.sum / self.count if self.count else None
            return {
                "count": self.count,
                "sum": self.sum,
                "mean": mean,
                "min": self.min,
                "max": self.max,
                "last": self.last,
            }


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors.

    One process-wide default registry (module functions below) so
    library code (data/loader.py, localization/driver.py) can record
    without plumbing a registry handle through every call chain; tests
    construct private registries or `reset()` the default.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                # Each metric gets its own lock: a hot counter on the
                # loader's producer thread must not contend with the
                # registry-structure lock held during snapshot().
                m = cls(name, threading.Lock())
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def snapshot(self) -> dict:
        """Serialize every metric into a plain-JSON dict, grouped by kind."""
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(items):
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def render_text(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the registry.

        The serving front end's ``GET /metrics`` serves this; any
        Prometheus-compatible scraper consumes it directly. Mapping:

          * dotted metric names sanitize to underscores
            (``serving.queue_wait_s`` -> ``serving_queue_wait_s``);
          * Counter -> ``<name>_total`` counter;
          * Gauge   -> gauge (unset gauges are omitted — Prometheus has
            no null and 0.0 would be a lie);
          * Histogram -> a ``<name>`` summary (``_count``/``_sum``, the
            two fields our streaming summary can expose exactly) plus
            ``<name>_min``/``<name>_max``/``<name>_last`` gauges — the
            registry keeps no quantile sketch (metrics.Histogram
            docstring), so no fabricated ``quantile`` labels.
        """
        with self._lock:
            items = sorted(self._metrics.items())
        lines = []

        def emit(name, kind, value):
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {float(value):g}")

        for name, m in items:
            pname = _prom_name(name)
            if isinstance(m, Counter):
                emit(f"{pname}_total", "counter", m.snapshot())
            elif isinstance(m, Gauge):
                v = m.snapshot()
                if v is not None:
                    emit(pname, "gauge", v)
            else:
                s = m.snapshot()
                lines.append(f"# TYPE {pname} summary")
                lines.append(f"{pname}_count {float(s['count']):g}")
                lines.append(f"{pname}_sum {float(s['sum']):g}")
                for field in ("min", "max", "last"):
                    if s[field] is not None:
                        emit(f"{pname}_{field}", "gauge", s[field])
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


_PROM_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Sanitize a dotted metric name into a valid Prometheus name."""
    name = _PROM_INVALID.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT


def counter(name: str) -> Counter:
    return _DEFAULT.counter(name)


def gauge(name: str) -> Gauge:
    return _DEFAULT.gauge(name)


def histogram(name: str) -> Histogram:
    return _DEFAULT.histogram(name)


def snapshot() -> dict:
    return _DEFAULT.snapshot()


def render_text() -> str:
    return _DEFAULT.render_text()


def reset() -> None:
    _DEFAULT.reset()
