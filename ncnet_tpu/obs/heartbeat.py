"""Stall heartbeat + hard-exit watchdog for long-running entry points.

Two failure modes show up on real TPU sessions and have, until now,
been handled by ad-hoc copies of the same thread-and-deadline pattern
in ``bench.py``/``utils/profiling.run_bench_matrix`` and
``tools/tpu_session.py``:

* a run goes QUIET — the process is alive but nothing has progressed
  for minutes (wedged tunnel, hung compile, starved input pipeline).
  :class:`Heartbeat` makes that visible: a daemon thread emits a
  periodic ``heartbeat`` event carrying the idle time since the last
  real (non-heartbeat) run-log event, and a one-shot ``stall`` event
  when the idle time crosses a threshold. Downstream, the run log tells
  you not just *that* the run died but *when it stopped progressing*.

* a run goes ZOMBIE — SIGALRM fencing can't fire because the main
  thread is stuck inside a C extension holding the GIL hostage, so the
  only way out is ``os._exit``. :class:`Watchdog` is that pattern made
  reusable: arm a deadline, a daemon thread hard-exits the process if
  it passes. ``run_bench_matrix`` and ``tpu_session`` now use it
  instead of their private ``deadline = [None]`` lists.

Both take an injectable ``clock`` so tests drive stall detection with a
fake clock instead of sleeping.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from . import metrics as _metrics


class Heartbeat:
    """Background thread emitting periodic ``heartbeat`` events on a RunLog.

    The first beat is emitted synchronously inside :meth:`start`, so
    even a seconds-long smoke run records at least one heartbeat event.
    A ``stall`` event is emitted once per stall episode: when
    ``idle_s`` (time since the run's last non-heartbeat event) first
    exceeds ``stall_after_s``, and again only after progress resumes
    and a new stall begins.
    """

    def __init__(
        self,
        runlog,
        interval_s: float = 30.0,
        stall_after_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.runlog = runlog
        self.interval_s = float(interval_s)
        # Default: four missed beats without progress is a stall.
        self.stall_after_s = (
            float(stall_after_s) if stall_after_s is not None
            else 4.0 * self.interval_s
        )
        self.clock = clock
        # Counters below are written by beat_once only: the heartbeat
        # thread, plus one synchronous seed call in start() made before
        # that thread exists. /healthz readers tolerate a stale value.
        # guarded-by: single-writer -- beat_once is heartbeat-thread-only
        self.beats = 0
        # guarded-by: single-writer -- beat_once is heartbeat-thread-only
        self.stalls = 0
        # guarded-by: single-writer -- beat_once is heartbeat-thread-only
        self._in_stall = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def in_stall(self) -> bool:
        """True while the run is inside a stall episode (idle time past
        ``stall_after_s`` and no progress since) — the serving front
        end's ``/healthz`` reports this so a load balancer can drain a
        wedged replica instead of timing requests out against it."""
        return self._in_stall

    def beat_once(self) -> dict:
        """Emit one heartbeat (and maybe a stall) event; returns the fields.

        Public so tests can drive stall detection with a fake clock and
        no thread.
        """
        now = self.clock()
        idle_s = now - self.runlog.last_progress_mono
        stalled = idle_s >= self.stall_after_s
        # Liveness as metrics, not just events: a scraper (or the fleet
        # dashboard) sees a wedged replica without reading its run log.
        registry = (getattr(self.runlog, "registry", None)
                    or _metrics.default_registry())
        if stalled and not self._in_stall:
            self._in_stall = True
            self.stalls += 1
            registry.counter("obs.heartbeat.stalls").inc()
            self.runlog.event("stall", idle_s=idle_s,
                              stall_after_s=self.stall_after_s)
            # Dump the flight ring at the START of the episode — the
            # events leading into the stall, written while the process
            # is still healthy enough to write them (obs/flight.py).
            try:
                from . import flight

                d = None
                path = getattr(self.runlog, "path", None)
                if path:
                    d = os.path.dirname(os.path.abspath(path)) or None
                flight.dump("stall", directory=d)
            except Exception:
                pass
        elif not stalled:
            self._in_stall = False
        registry.gauge("obs.heartbeat.in_stall").set(
            1.0 if self._in_stall else 0.0)
        self.beats += 1
        fields = {"idle_s": idle_s, "stalled": stalled, "beat": self.beats}
        self.runlog.event("heartbeat", **fields)
        return fields

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat_once()
            except Exception:
                # A telemetry thread must never propagate into stderr
                # spam or take the interpreter down at shutdown.
                return

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self.beat_once()
        self._thread = threading.Thread(
            target=self._loop, name="obs-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)


class Watchdog:
    """Hard-exit deadline for sections SIGALRM fencing cannot cover.

    ``run_with_alarm`` (utils/profiling.py) handles the common case,
    but a main thread stuck inside a blocking C call never services
    the alarm. This watchdog runs a daemon thread that polls a shared
    deadline and calls ``on_expire`` (default ``os._exit(exit_code)``)
    once it is passed — the pattern previously duplicated as
    ``deadline = [None]`` + local ``_watchdog`` closures in
    ``run_bench_matrix`` and ``tools/tpu_session.py``.

    Usage::

        wd = Watchdog(label="phase").start()
        wd.arm(timeout_s + 120)   # hard ceiling past the soft alarm
        ...                        # fenced work
        wd.disarm()
    """

    def __init__(
        self,
        label: str = "watchdog",
        exit_code: int = 3,
        poll_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_expire: Optional[Callable[[], None]] = None,
        log: Callable[[str], None] = lambda msg: None,
    ):
        self.label = label
        self.exit_code = exit_code
        self.poll_s = float(poll_s)
        self.clock = clock
        self.on_expire = on_expire
        self.log = log
        self._deadline: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def arm(self, seconds: float) -> None:
        with self._lock:
            self._deadline = self.clock() + float(seconds)

    def disarm(self) -> None:
        with self._lock:
            self._deadline = None

    def expired(self) -> bool:
        with self._lock:
            d = self._deadline
        return d is not None and self.clock() > d

    def check(self) -> bool:
        """One poll step; fires ``on_expire`` when past the deadline.

        Returns True when it fired. Public for fake-clock tests —
        the thread loop is just this on a timer.
        """
        if not self.expired():
            return False
        self.log(f"[{self.label}] hard deadline exceeded; exiting "
                 f"{self.exit_code}")
        # Last act before the hard exit: dump the flight ring — the
        # only record of what the process was doing when it wedged.
        try:
            from . import flight

            flight.dump(f"watchdog-{self.label}", force=True)
        except Exception:
            pass
        if self.on_expire is not None:
            self.on_expire()
        else:
            os._exit(self.exit_code)
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self.check():
                return

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"obs-watchdog-{self.label}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # No join: the thread sleeps up to poll_s and is a daemon; a
        # disarm + set is enough to make it inert.
